"""Device-path tests: PulsarBatch freeze + batched injection ops.

Statistical validation strategy per SURVEY.md section 4: the device path
uses jax.random (different streams than the oracle's legacy RNG), so
agreement is checked on distributional properties (variances, epoch
correlation structure, HD cross-correlations) and on exact values for the
deterministic ops (CW catalog).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pta_replicator_tpu.batch import freeze
from pta_replicator_tpu.models import batched as B
from pta_replicator_tpu.models.cgw import add_catalog_of_cws, cw_delay
from pta_replicator_tpu.models.gwb import gwb_time_series
from pta_replicator_tpu.ops.orf import assemble_orf
from pta_replicator_tpu.ops.quantize import quantize


@pytest.fixture(scope="module")
def batch(partim_small_module):
    from pta_replicator_tpu import load_from_directories, make_ideal

    pardir, timdir = partim_small_module
    psrs = load_from_directories(pardir, timdir, num_psrs=3)
    for p in psrs:
        make_ideal(p)
    return freeze(psrs), psrs


def test_freeze_shapes_and_masks(batch):
    b, psrs = batch
    assert b.npsr == 3 and b.ntoa_max == 122
    assert np.all(np.asarray(b.mask) == 1.0)  # equal-length fixture
    assert b.names == ("JPSR00", "JPSR01", "JPSR02")
    # epoch structure matches the oracle quantization
    bins = quantize(psrs[0].toas.get_mjds(), dt=0.1)
    assert int(b.epoch_mask[0].sum()) == bins.nepochs
    np.testing.assert_array_equal(np.asarray(b.epoch_index[0]), bins.epoch_index)
    # unit phat
    np.testing.assert_allclose(np.linalg.norm(np.asarray(b.phat), axis=1), 1.0)


def test_white_noise_variance(batch):
    b, _ = batch
    keys = jax.random.split(jax.random.PRNGKey(0), 3000)
    d = jax.vmap(lambda k: B.white_noise_delays(k, b, efac=1.5, log10_equad=-6.0))(keys)
    var = np.var(np.asarray(d), axis=0)
    expect = 1.5**2 * np.asarray(b.errors_s) ** 2 + 1.5**2 * (1e-6) ** 2
    np.testing.assert_allclose(var, expect, rtol=0.15)


def test_white_noise_per_backend_gather():
    """Distinct per-backend EFACs land on the right TOAs through the
    freeze-built integer gather tables (device analog of the reference's
    string-flag loops, white_noise.py:95-103)."""
    from types import SimpleNamespace

    from pta_replicator_tpu.io.tim import fabricate_toas

    psrs = []
    for i in range(2):
        toas = fabricate_toas(np.linspace(53000, 55000, 80), 0.5)
        for j in range(toas.ntoas):  # alternate two backends
            toas.flags[j] = {"f": "RCVR_A" if j % 2 == 0 else "RCVR_B"}
        psrs.append(SimpleNamespace(
            toas=toas, loc={"RAJ": 1.0 + i, "DECJ": 10.0 * i}, name=f"T{i}"
        ))
    from pta_replicator_tpu.batch import freeze

    b = freeze(psrs, flagid="f")
    assert b.backend_names == ("RCVR_A", "RCVR_B")
    efac = jnp.asarray([[1.0, 4.0], [2.0, 8.0]])  # (Np, NB)
    keys = jax.random.split(jax.random.PRNGKey(10), 3000)
    d = jax.vmap(lambda k: B.white_noise_delays(k, b, efac=efac))(keys)
    std = np.asarray(d).std(axis=0) / np.asarray(b.errors_s)
    idx = np.asarray(b.backend_index)
    for p in range(2):
        for bk in range(2):
            got = std[p][idx[p] == bk].mean()
            np.testing.assert_allclose(got, float(efac[p, bk]), rtol=0.05)


def test_jitter_epoch_structure(batch):
    b, _ = batch
    d = B.jitter_delays(jax.random.PRNGKey(1), b, log10_ecorr=np.log10(3e-7))
    d = np.asarray(d)
    idx = np.asarray(b.epoch_index)
    for p in range(b.npsr):
        for e in np.unique(idx[p]):
            vals = d[p][idx[p] == e]
            assert np.allclose(vals, vals[0])  # shared draw within epoch
    keys = jax.random.split(jax.random.PRNGKey(2), 4000)
    dd = jax.vmap(lambda k: B.jitter_delays(k, b, np.log10(3e-7)))(keys)
    np.testing.assert_allclose(np.asarray(dd).var(axis=0).mean(), (3e-7) ** 2, rtol=0.1)


def test_red_noise_variance(batch):
    """Per-TOA variance of the red-noise delay equals the summed prior."""
    b, _ = batch
    keys = jax.random.split(jax.random.PRNGKey(3), 4000)
    d = jax.vmap(lambda k: B.red_noise_delays(k, b, -14.0, 4.33, nmodes=30))(keys)
    var = np.asarray(d).var(axis=0).mean(axis=1)  # (Np,)
    from pta_replicator_tpu.constants import YEAR_IN_SEC

    T = np.asarray(b.tspan_s)
    f = np.arange(1, 31)[None, :] / T[:, None]
    prior = (
        1e-28 * (f * YEAR_IN_SEC) ** (-4.33) / (12 * np.pi**2 * T[:, None])
        * YEAR_IN_SEC**3
    )
    # each mode contributes prior_k * (sin^2 + cos^2) = prior_k per TOA
    np.testing.assert_allclose(var, prior.sum(axis=1), rtol=0.1)


def test_gwb_hellings_downs_correlations(batch):
    """Realization-averaged cross-pulsar correlations recover the ORF."""
    b, psrs = batch
    orf = assemble_orf(_locs(psrs), lmax=0)
    M = np.linalg.cholesky(orf)
    keys = jax.random.split(jax.random.PRNGKey(4), 1500)
    d = jax.vmap(
        lambda k: B.gwb_delays(k, b, -14.0, 4.33, M, npts=200, howml=4)
    )(keys)
    d = np.asarray(d)  # (R, Np, Nt)
    cov = np.einsum("ran,rbn->ab", d, d) / d.shape[0] / d.shape[2]
    corr = cov / np.sqrt(np.outer(np.diag(cov), np.diag(cov)))
    expect = orf / 2.0
    np.testing.assert_allclose(corr, expect, atol=0.08)


def _locs(psrs):
    from pta_replicator_tpu.ops.coords import pulsar_ra_dec

    locs = np.zeros((len(psrs), 2))
    for i, p in enumerate(psrs):
        ra, dec = pulsar_ra_dec(p.loc, p.name)
        locs[i] = ra, np.pi / 2 - dec
    return locs


def test_irfft_equals_hermitian_pack_ifft():
    """The device path's irfft shortcut matches the oracle's packing."""
    rng = np.random.default_rng(0)
    nf = 65
    w = rng.normal(size=(2, nf)) + 1j * rng.normal(size=(2, nf))
    w[:, 0] = 0.0
    w[:, -1] = 0.0
    oracle = gwb_time_series(w, np.eye(2), np.ones(nf), dt_grid=1.0, npts=100)
    direct = np.fft.irfft(w, n=2 * nf - 2, axis=-1)[:, 10:110]
    np.testing.assert_allclose(oracle, direct, atol=1e-12)


def test_gwb_matmul_synthesis_matches_fft(batch):
    """The MXU matmul synthesis is the same linear map as the Bluestein
    irfft it replaces (exact in f64)."""
    b, psrs = batch
    orf = assemble_orf(_locs(psrs), lmax=0)
    M = np.linalg.cholesky(orf)
    key = jax.random.PRNGKey(11)
    a = B.gwb_delays(key, b, -14.0, 4.33, M, npts=150, howml=6.0, synthesis="fft")
    c = B.gwb_delays(key, b, -14.0, 4.33, M, npts=150, howml=6.0, synthesis="matmul")
    rms = float(jnp.sqrt(jnp.mean(a**2)))
    np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-9 * rms)


def test_uniform_grid_interp_matches_np_interp():
    rng = np.random.default_rng(9)
    series = rng.normal(size=(4, 50))
    grid = np.linspace(-3.0, 7.0, 50)
    t = np.sort(rng.uniform(-3.0, 7.0, size=(4, 200)), axis=1)
    out = np.asarray(B.uniform_grid_interp(jnp.asarray(t), -3.0, 7.0, jnp.asarray(series)))
    for i in range(4):
        np.testing.assert_allclose(out[i], np.interp(t[i], grid, series[i]), atol=1e-12)


@pytest.mark.parametrize(
    "opts",
    [
        dict(),
        dict(libstempo_convention=True),
        dict(logf=True, fmin=2e-9, fmax=4e-8),
        dict(fmin=1.5e-9, fmax=3e-8),
        dict(modes=np.arange(1, 13) / 2.1e8),
        dict(tspan_s=5.5e8),
        dict(phase_shift=np.linspace(0, 2 * np.pi, 30, endpoint=False)),
    ],
    ids=["default", "libstempo", "logf", "linear", "modes", "tspan",
         "pshift"],
)
def test_red_noise_device_option_parity(batch, opts):
    """Every frequency-grid/convention option of the oracle design matrix
    (reference red_noise.py:36-103) produces identical delays on the
    device path when fed the same coefficient stream."""
    from pta_replicator_tpu.ops.fourier import (
        fourier_basis,
        fourier_frequencies,
        powerlaw_prior,
    )
    from pta_replicator_tpu.constants import DAY_IN_SEC

    b, psrs = batch
    opts = dict(opts)
    shift = opts.pop("phase_shift", None)
    nmodes = 30 if "modes" not in opts else len(opts["modes"])
    rng = np.random.default_rng(21)
    eps = rng.normal(size=(b.npsr, 2 * nmodes))

    dev = B.red_noise_delays(
        jax.random.PRNGKey(0), b, -14.0, 4.33, nmodes=nmodes,
        eps=eps, modes=opts.get("modes"),
        logf=opts.get("logf", False),
        fmin=opts.get("fmin"), fmax=opts.get("fmax"),
        phase_shift=None if shift is None else jnp.asarray(shift)[None, :],
        libstempo_convention=opts.get("libstempo_convention", False),
        tspan_s=opts.get("tspan_s"),
    )

    for i, p in enumerate(psrs):
        # oracle basis with the same options and coefficient stream.
        # NOTE the time conventions: device times are batch-epoch-relative,
        # oracle times absolute — identical bases except for a per-mode
        # phase, which the libstempo convention (t - t0) removes and the
        # default convention changes only which N(0,1) pair multiplies
        # the quadrature; to compare exactly we evaluate the oracle basis
        # on the device's relative times.
        toas_rel = np.asarray(b.toas_s[i], np.float64)
        toas_abs = p.toas.get_mjds() * DAY_IN_SEC
        T = (
            opts.get("tspan_s")
            or float(toas_abs.max() - toas_abs.min())
        )
        f = fourier_frequencies(
            T, nmodes=nmodes, logf=opts.get("logf", False),
            fmin=opts.get("fmin"), fmax=opts.get("fmax"),
            modes=opts.get("modes"),
        )
        F = fourier_basis(
            toas_rel, f, phase_shift=shift,
            libstempo_convention=opts.get("libstempo_convention", False),
        )
        prior = powerlaw_prior(np.repeat(f, 2), -14.0, 4.33, T)
        expect = F @ (np.sqrt(prior) * eps[i])
        np.testing.assert_allclose(
            np.asarray(dev[i]), expect, rtol=1e-9, atol=1e-12
        )


def test_red_noise_pshift_statistics(batch):
    """Random per-mode phase shifts preserve the delay variance (the PSD
    is phase-blind) while decorrelating individual realizations."""
    b, _ = batch
    keys = jax.random.split(jax.random.PRNGKey(3), 400)
    base = jax.vmap(
        lambda k: B.red_noise_delays(k, b, -13.6, 4.0)
    )(keys)
    shifted = jax.vmap(
        lambda k: B.red_noise_delays(k, b, -13.6, 4.0, pshift=True)
    )(keys)
    v0, v1 = float(jnp.var(base)), float(jnp.var(shifted))
    assert abs(v1 / v0 - 1.0) < 0.2
    assert not np.allclose(np.asarray(base[0]), np.asarray(shifted[0]))


def test_cgw_catalog_matches_oracle(batch):
    """Deterministic op: device catalog == oracle catalog, exactly."""
    b, psrs = batch
    n = 700
    rng = np.random.default_rng(5)
    cat = dict(
        gwtheta=np.arccos(rng.uniform(-1, 1, n)),
        gwphi=rng.uniform(0, 2 * np.pi, n),
        mc=10 ** rng.uniform(8, 9.5, n),
        dist=rng.uniform(10, 500, n),
        fgw=10 ** rng.uniform(-8.8, -7.5, n),
        phase0=rng.uniform(0, 2 * np.pi, n),
        psi=rng.uniform(0, np.pi, n),
        inc=np.arccos(rng.uniform(-1, 1, n)),
    )
    tref = 53000 * 86400
    dev = B.cgw_catalog_delays(b, *cat.values(), tref_s=tref, chunk=128)
    for i, p in enumerate(psrs):
        add_catalog_of_cws(
            p,
            gwtheta_list=cat["gwtheta"], gwphi_list=cat["gwphi"],
            mc_list=cat["mc"], dist_list=cat["dist"], fgw_list=cat["fgw"],
            phase0_list=cat["phase0"], psi_list=cat["psi"], inc_list=cat["inc"],
            tref=tref,
        )
        oracle = p.added_signals_time[f"{p.name}_cw_catalog"]
        np.testing.assert_allclose(np.asarray(dev[i]), oracle, rtol=1e-8, atol=1e-15)


@pytest.mark.parametrize(
    "pskw",
    [
        dict(pdist="per_source"),
        dict(pphase="per_source"),
        dict(pphase="per_source", mode=dict(evolve=False, phase_approx=True)),
        dict(pphase="per_source", mode=dict(evolve=False, phase_approx=False)),
    ],
    ids=["pdist-vec", "pphase-evolve", "pphase-approx", "pphase-mono"],
)
def test_cgw_catalog_pphase_pdist_matches_oracle(batch, pskw):
    """Per-source pulsar distances and explicit pulsar-term phases agree
    with the oracle path (reference deterministic.py:99-108) in every
    evolution mode."""
    b, psrs = batch
    n = 40
    rng = np.random.default_rng(15)
    cat = dict(
        gwtheta=np.arccos(rng.uniform(-1, 1, n)),
        gwphi=rng.uniform(0, 2 * np.pi, n),
        mc=10 ** rng.uniform(8, 9.4, n),
        dist=rng.uniform(10, 500, n),
        fgw=10 ** rng.uniform(-8.8, -7.6, n),
        phase0=rng.uniform(0, 2 * np.pi, n),
        psi=rng.uniform(0, np.pi, n),
        inc=np.arccos(rng.uniform(-1, 1, n)),
    )
    pskw = dict(pskw)
    mode = pskw.pop("mode", {})
    kw = {k: rng.uniform(0.4, 3.0, n) if k == "pdist"
          else rng.uniform(0, 2 * np.pi, n) for k in pskw}
    tref = 53000 * 86400
    dev = B.cgw_catalog_delays(b, *cat.values(), tref_s=tref, **kw, **mode)
    sig = f"cw_pp_{'-'.join(sorted(kw))}_{sorted(mode.items())}"
    for i, p in enumerate(psrs):
        add_catalog_of_cws(
            p,
            gwtheta_list=cat["gwtheta"], gwphi_list=cat["gwphi"],
            mc_list=cat["mc"], dist_list=cat["dist"], fgw_list=cat["fgw"],
            phase0_list=cat["phase0"], psi_list=cat["psi"],
            inc_list=cat["inc"], tref=tref, signal_name=sig,
            evolve=mode.get("evolve", True),
            phase_approx=mode.get("phase_approx", False),
            **kw,
        )
        oracle = p.added_signals_time[f"{p.name}_{sig}"]
        np.testing.assert_allclose(
            np.asarray(dev[i]), oracle, rtol=1e-8, atol=1e-15
        )


@pytest.mark.parametrize(
    "mode",
    [
        dict(evolve=True, phase_approx=False),
        dict(evolve=False, phase_approx=True),
        dict(evolve=False, phase_approx=False),
        dict(evolve=True, phase_approx=False, psr_term=False),
    ],
)
def test_cgw_pallas_kernel_matches_scan(batch, mode):
    """The Pallas kernel (interpret mode on CPU) is the same linear map as
    the portable scan backend, for every evolution mode."""
    b, _ = batch
    n = 300
    rng = np.random.default_rng(6)
    cat = dict(
        gwtheta=np.arccos(rng.uniform(-1, 1, n)),
        gwphi=rng.uniform(0, 2 * np.pi, n),
        mc=10 ** rng.uniform(8, 9.8, n),
        dist=rng.uniform(10, 500, n),
        fgw=10 ** rng.uniform(-8.8, -7.5, n),
        phase0=rng.uniform(0, 2 * np.pi, n),
        psi=rng.uniform(0, np.pi, n),
        inc=np.arccos(rng.uniform(-1, 1, n)),
    )
    tref = 53000 * 86400
    kw = dict(tref_s=tref, pdist=1.3, **mode)
    scan = B.cgw_catalog_delays(b, *cat.values(), chunk=64, backend="scan", **kw)
    pallas = B.cgw_catalog_delays(
        b, *cat.values(), backend="pallas_interpret", **kw
    )
    rms = float(jnp.sqrt(jnp.mean(scan**2)))
    np.testing.assert_allclose(
        np.asarray(pallas), np.asarray(scan), atol=1e-9 * rms, rtol=1e-7
    )


def test_cgw_pallas_backend_retired(batch):
    """backend='pallas' was retired in round 5 and must raise with a
    pointer to the rationale, not silently fall back or try to compile
    Mosaic on an unknown platform."""
    b, _ = batch
    cat = [np.array([1.0]), np.array([0.5]), np.array([1e9]),
           np.array([100.0]), np.array([1e-8]), np.array([0.3]),
           np.array([0.1]), np.array([0.7])]
    with pytest.raises(ValueError, match="retired"):
        B.cgw_catalog_delays(b, *cat, backend="pallas")
    with pytest.raises(ValueError, match="unknown CW-catalog backend"):
        B.cgw_catalog_delays(b, *cat, backend="numba")


def test_cgw_pallas_nan_guard(batch):
    """Merged binaries (past-merger chirp) inject zeros, not NaNs, in both
    backends (reference deterministic.py:433-438)."""
    b, _ = batch
    cat = dict(
        gwtheta=np.array([1.0, 2.0]),
        gwphi=np.array([0.5, 4.0]),
        mc=np.array([5e9, 1e9]),  # first source merges before the data end
        dist=np.array([20.0, 100.0]),
        fgw=np.array([3e-7, 1e-8]),
        phase0=np.array([0.3, 2.0]),
        psi=np.array([0.1, 1.1]),
        inc=np.array([0.7, 2.2]),
    )
    scan = B.cgw_catalog_delays(b, *cat.values(), backend="scan")
    pallas = B.cgw_catalog_delays(b, *cat.values(), backend="pallas_interpret")
    assert bool(jnp.all(jnp.isfinite(scan)))
    assert bool(jnp.all(jnp.isfinite(pallas)))
    np.testing.assert_allclose(np.asarray(pallas), np.asarray(scan), rtol=1e-7)


def test_red_noise_explicit_modes_device(batch):
    """Explicit mode frequencies drive the device basis (variance equals
    the summed prior at those frequencies)."""
    b, _ = batch
    from pta_replicator_tpu.constants import YEAR_IN_SEC

    modes = np.linspace(2e-9, 2e-8, 10)
    keys = jax.random.split(jax.random.PRNGKey(8), 4000)
    d = jax.vmap(
        lambda k: B.red_noise_delays(k, b, -14.0, 4.33, modes=modes)
    )(keys)
    var = np.asarray(d).var(axis=0).mean(axis=1)
    T = np.asarray(b.tspan_s)
    prior = (
        1e-28 * (modes[None, :] * YEAR_IN_SEC) ** (-4.33)
        / (12 * np.pi**2 * T[:, None]) * YEAR_IN_SEC**3
    )
    np.testing.assert_allclose(var, prior.sum(axis=1), rtol=0.1)


def test_gw_memory_matches_oracle(batch):
    b, psrs = batch
    from pta_replicator_tpu.models.bursts import add_gw_memory

    args = dict(strain=5e-15, gwtheta=1.1, gwphi=2.3, bwm_pol=0.7)
    t0 = float(psrs[0].toas.get_mjds()[40])
    dev = B.gw_memory_delays(b, args["strain"], args["gwtheta"],
                             args["gwphi"], args["bwm_pol"], t0)
    for i, p in enumerate(psrs):
        add_gw_memory(p, t0_mjd=t0, **args)
        oracle = p.added_signals_time[f"{p.name}_gw_memory"]
        # atol floor: earlier tests in this module injected signals into
        # the shared pulsars, shifting their TOAs at the microsecond
        # level relative to the frozen batch (strain * 1e-6 s ~ 5e-21)
        np.testing.assert_allclose(np.asarray(dev[i]), oracle, rtol=1e-9,
                                   atol=1e-19)


def test_burst_and_transient_match_oracle(batch):
    b, psrs = batch
    from pta_replicator_tpu.models.bursts import add_burst, add_noise_transient

    t0 = float(np.asarray(b.toas_s).mean())
    width = 100 * 86400.0
    hp = lambda t: 4e-9 * np.exp(-0.5 * ((t - t0) / width) ** 2)
    hc = lambda t: 2e-9 * np.sin((t - t0) / width) * np.exp(
        -0.5 * ((t - t0) / width) ** 2
    )
    lo, hi = t0 - 8 * width, t0 + 8 * width
    grid = np.linspace(lo, hi, 16384)
    dev = B.burst_delays(b, 0.9, 4.1, hp(grid), hc(grid), lo, hi, psi=0.6)
    tref = float(b.tref_mjd) * 86400.0
    for i, p in enumerate(psrs):
        add_burst(p, 0.9, 4.1, hp, hc, psi=0.6, tref=tref)
        oracle = p.added_signals_time[f"{p.name}_burst"]
        rms = max(np.sqrt(np.mean(oracle**2)), 1e-30)
        np.testing.assert_allclose(np.asarray(dev[i]), oracle,
                                   atol=1e-5 * rms)

    devt = B.transient_delays(b, 1, hp(grid), lo, hi)
    assert np.allclose(np.asarray(devt[0]), 0.0)
    add_noise_transient(psrs[1], hp, tref=tref)
    oracle = psrs[1].added_signals_time[f"{psrs[1].name}_noise_transient"]
    np.testing.assert_allclose(
        np.asarray(devt[1]), oracle, atol=1e-5 * np.sqrt(np.mean(oracle**2))
    )


def test_recipe_realize_shapes(batch):
    b, psrs = batch
    orf = assemble_orf(_locs(psrs), lmax=0)
    recipe = B.Recipe(
        efac=jnp.ones(3),
        log10_ecorr=jnp.full(3, -6.5),
        rn_log10_amplitude=jnp.full(3, -14.0),
        rn_gamma=jnp.full(3, 4.33),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        orf_cholesky=jnp.asarray(np.linalg.cholesky(orf)),
    )
    res = B.realize(jax.random.PRNGKey(7), b, recipe, nreal=4)
    assert res.shape == (4, 3, 122)
    assert bool(jnp.all(jnp.isfinite(res)))
    # residualized: weighted mean ~ 0 per pulsar
    w = np.asarray(b.mask / b.errors_s**2)
    means = np.einsum("rpn,pn->rp", np.asarray(res), w) / w.sum(axis=1)
    assert np.abs(means).max() < 1e-18


def test_gwb_spectral_slope(uniform_batch):
    """Realization-averaged periodogram of injected GWB delays recovers
    the residual-PSD power law f^-gamma (within Hann-window leakage bias
    for this steep spectrum)."""
    b = uniform_batch
    M = np.sqrt(2.0) * np.eye(2)
    keys = jax.random.split(jax.random.PRNGKey(3), 300)
    d = np.asarray(jax.vmap(
        lambda k: B.gwb_delays(k, b, -14.0, 4.33, M, npts=400, howml=4)
    )(keys))
    t = np.asarray(b.toas_s)[0]
    w = np.hanning(d.shape[-1])
    P = (np.abs(np.fft.rfft(d * w, axis=-1)) ** 2).mean(axis=(0, 1))
    f = np.fft.rfftfreq(d.shape[-1], t[1] - t[0])
    sel = (f > 3.0 / (t[-1] - t[0])) & (f < 0.2 * f[-1])
    slope = np.polyfit(np.log(f[sel]), np.log(P[sel]), 1)[0]
    assert abs(slope - (-4.33)) < 0.45


@pytest.fixture(scope="module")
def uniform_batch():
    """Two pulsars on a uniform 512-point TOA grid (for spectral tests)."""
    from types import SimpleNamespace

    from pta_replicator_tpu.io.tim import fabricate_toas

    psrs = [
        SimpleNamespace(
            toas=fabricate_toas(np.linspace(50000, 60000, 512), 0.5),
            loc={"RAJ": i + 0.5, "DECJ": 5.0 * i},
            name=f"U{i}",
        )
        for i in range(2)
    ]
    return freeze(psrs)


def test_recipe_parameter_sweep_vmap(batch):
    """Recipe array leaves are traced: vmapping realization over a grid of
    GWB amplitudes sweeps parameters without retracing, and the output RMS
    grows monotonically with amplitude."""
    b, psrs = batch
    orf = assemble_orf(_locs(psrs), lmax=0)
    M = jnp.asarray(np.linalg.cholesky(orf))
    amps = jnp.asarray([-15.0, -14.0, -13.0])

    def realize_at(log10_A):
        recipe = B.Recipe(
            gwb_log10_amplitude=log10_A,
            gwb_gamma=jnp.asarray(4.33),
            orf_cholesky=M,
            gwb_npts=150,
            gwb_howml=4.0,
        )
        keys = jax.random.split(jax.random.PRNGKey(17), 16)
        d = jax.vmap(lambda k: B.realization_delays(k, b, recipe))(keys)
        return jnp.sqrt(jnp.mean(d**2))

    rms = np.asarray(jax.jit(jax.vmap(realize_at))(amps))
    assert rms[0] < rms[1] < rms[2]
    # each decade in amplitude is a decade in RMS
    np.testing.assert_allclose(rms[2] / rms[1], 10.0, rtol=0.05)


def test_recipe_gwb_without_orf_is_uncorrelated(batch):
    """orf_cholesky=None means the reference's no_correlations mode:
    autocorrelations present, cross-correlations ~ 0."""
    b, _ = batch
    recipe = B.Recipe(
        gwb_log10_amplitude=jnp.asarray(-13.5),
        gwb_gamma=jnp.asarray(4.33),
        gwb_npts=150,
        gwb_howml=4.0,
    )
    keys = jax.random.split(jax.random.PRNGKey(21), 800)
    d = np.asarray(jax.vmap(
        lambda k: B.realization_delays(k, b, recipe)
    )(keys))
    cov = np.einsum("ran,rbn->ab", d, d) / (d.shape[0] * d.shape[2])
    corr = cov / np.sqrt(np.outer(np.diag(cov), np.diag(cov)))
    off = corr[~np.eye(b.npsr, dtype=bool)]
    assert np.all(np.abs(off) < 0.1)
    assert np.all(np.diag(cov) > 0)


def test_recipe_gwb_turnover(batch):
    """Turnover recipe suppresses low-frequency GWB power relative to the
    plain power law (same keys, same draws)."""
    b, psrs = batch
    orf = assemble_orf(_locs(psrs), lmax=0)
    M = jnp.asarray(np.linalg.cholesky(orf))
    base = dict(
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        orf_cholesky=M,
        gwb_npts=150,
        gwb_howml=4.0,
    )
    keys = jax.random.split(jax.random.PRNGKey(13), 60)
    plain = jax.vmap(lambda k: B.realization_delays(k, b, B.Recipe(**base)))(keys)
    turn = jax.vmap(
        lambda k: B.realization_delays(
            k, b, B.Recipe(
                gwb_turnover=True,
                gwb_f0=jnp.asarray(2e-8),
                gwb_power=jnp.asarray(2.0),
                **base,
            )
        )
    )(keys)
    # the turnover removes most low-frequency (dominant) power
    assert float(jnp.mean(turn**2)) < 0.5 * float(jnp.mean(plain**2))


def test_fit_subtract_removes_quadratic(batch):
    b, _ = batch
    t = np.asarray(b.toas_s)
    fake = 1e-6 + 3e-14 * t + 5e-22 * t**2
    out = np.asarray(B.quadratic_fit_subtract(jnp.asarray(fake), b))
    assert np.abs(out).max() < 1e-12


def test_gwb_synthesis_precision_knob(batch):
    """The synthesis_precision knob plumbs through gwb_delays and Recipe;
    'highest' must agree with the default on CPU (same arithmetic)."""
    from pta_replicator_tpu.ops.orf import hellings_downs_matrix

    batch, _ = batch
    phat = np.asarray(batch.phat)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]), np.arccos(phat[:, 2])], axis=1
    )
    M = jnp.asarray(np.linalg.cholesky(hellings_downs_matrix(locs)))
    key = jax.random.PRNGKey(5)
    kw = dict(npts=100, howml=4.0)
    d_def = B.gwb_delays(key, batch, -14.0, 4.33, M, **kw)
    d_hi = B.gwb_delays(
        key, batch, -14.0, 4.33, M, synthesis_precision="highest", **kw
    )
    np.testing.assert_allclose(np.asarray(d_def), np.asarray(d_hi), rtol=1e-12)


def test_design_fit_subtract_matches_oracle_full_fit(batch):
    """The batched device refit over the full design tensor produces the
    same post-fit residual structure as the oracle WLS full-model fit,
    per pulsar — including with zero-padding columns."""
    from pta_replicator_tpu.timing.fit import design_tensor, wls_fit

    b, psrs = batch
    D, names = design_tensor(psrs, ntoa_max=b.ntoa_max)
    rng = np.random.default_rng(8)
    delays = rng.normal(scale=1e-6, size=(b.npsr, b.ntoa_max))

    out = np.asarray(B.design_fit_subtract(jnp.asarray(delays), b, D))
    for i, psr in enumerate(psrs):
        n = psr.toas.ntoas
        M = D[i, :n, :]
        keep = np.sqrt((M**2).sum(0)) > 0  # this pulsar's real columns
        _, post = wls_fit(delays[i, :n], psr.toas.errors_s, M[:, keep])
        np.testing.assert_allclose(out[i, :n], post, rtol=0, atol=1e-12)

    # an extra all-zero padding column must not change anything
    D2 = np.concatenate([D, np.zeros_like(D[..., :1])], axis=-1)
    out2 = np.asarray(B.design_fit_subtract(jnp.asarray(delays), b, D2))
    np.testing.assert_allclose(out2, out, rtol=0, atol=1e-13)


def test_realize_with_design_fit(batch):
    """realize(fit=True) uses the full design tensor when the recipe
    carries one; residuals lose the span of every design column."""
    from pta_replicator_tpu.timing.fit import design_tensor

    b, psrs = batch
    D, _ = design_tensor(psrs, ntoa_max=b.ntoa_max)
    recipe = B.Recipe(
        efac=jnp.ones(b.npsr),
        rn_log10_amplitude=jnp.full(b.npsr, -14.0),
        rn_gamma=jnp.full(b.npsr, 4.33),
        fit_design=jnp.asarray(D),
    )
    out = B.realize(jax.random.PRNGKey(3), b, recipe, nreal=4, fit=True)
    assert out.shape == (4, b.npsr, b.ntoa_max)
    # the fit is (ridge-regularized) idempotent: a second application of
    # the design fit removes essentially nothing more. NOTE residualize
    # runs after the fit in realize, so re-fit the *residualized* output
    refit = np.asarray(
        jax.vmap(lambda d: B.design_fit_subtract(d, b, jnp.asarray(D)))(out)
    )
    rms = float(np.sqrt(np.mean(np.asarray(out) ** 2)))
    # bound: ridge (1e-10 relative) + the residualize weighted-mean step
    # between the two applications
    assert float(np.max(np.abs(refit - np.asarray(out)))) < 1e-5 * rms


def test_quadratic_fit_projects_mean():
    """The quad fit's constant column absorbs the weighted mean exactly
    (its normal equations run at precision='highest' — on TPU the bf16
    default left a ~5% un-projected component), which is what lets
    finalize_residuals skip the residualize pass after the quad fit."""
    from pta_replicator_tpu.batch import synthetic_batch

    batch = synthetic_batch(npsr=6, ntoa=256, nbackend=2, seed=3)
    recipe = B.Recipe(
        efac=jnp.ones((6, 2)),
        rn_log10_amplitude=jnp.full(6, -13.5),
        rn_gamma=jnp.full(6, 3.0),
    )
    d = B.realization_delays(jax.random.PRNGKey(2), batch, recipe)
    q = B.quadratic_fit_subtract(d, batch)
    rms = float(jnp.sqrt(jnp.mean(q**2)))

    # weighted mean of the fit residual vanishes...
    w = batch.mask / batch.errors_s**2
    mean = np.asarray(jnp.sum(w * q, axis=-1) / jnp.sum(w, axis=-1))
    assert np.abs(mean).max() < 1e-9 * rms

    # ...so the fit path of finalize_residuals equals fit-then-residualize
    a = np.asarray(B.finalize_residuals(d, batch, recipe, fit=True))
    b = np.asarray(B.residualize(q, batch))
    assert np.abs(a - b).max() < 1e-9 * rms

    # and the design-fit path retains the residualize pass (a design
    # tensor need not span a constant): fit a pure-slope column and check
    # the weighted mean is still removed
    import dataclasses

    design = jnp.stack([batch.toas_s * batch.mask], axis=-1)  # (Np, Nt, 1)
    r2 = dataclasses.replace(recipe, fit_design=design)
    out = B.finalize_residuals(d, batch, r2, fit=True)
    mean2 = np.asarray(
        jnp.sum(w * out, axis=-1) / jnp.sum(w, axis=-1)
    )
    assert np.abs(mean2).max() < 1e-9 * rms


def test_realization_delays_stream_layout():
    """realization_delays consumes split(key, 5) in (wn, ecorr, rn,
    chrom, gwb) order — the STREAM_VERSION contract checkpointed sweeps
    rely on. Bitwise: the summed per-op delays under that split
    reproduce it."""
    from pta_replicator_tpu.batch import synthetic_batch

    b = synthetic_batch(npsr=4, ntoa=256, nbackend=2, seed=2)
    recipe = B.Recipe(
        efac=jnp.ones((4, 2)),
        log10_equad=jnp.full((4, 2), -6.5),
        log10_ecorr=jnp.full((4, 2), -6.6),
        rn_log10_amplitude=jnp.full(4, -13.8),
        rn_gamma=jnp.full(4, 3.5),
        chrom_log10_amplitude=jnp.full(4, -13.9),
        chrom_gamma=jnp.full(4, 2.5),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        gwb_npts=64,
        gwb_howml=4.0,
    )
    key = jax.random.PRNGKey(7)
    total = B.realization_delays(key, b, recipe)
    k_wn, k_ec, k_rn, k_chrom, k_gwb = jax.random.split(key, 5)
    parts = (
        B.white_noise_delays(k_wn, b, efac=recipe.efac,
                             log10_equad=recipe.log10_equad)
        + B.jitter_delays(k_ec, b, recipe.log10_ecorr)
        + B.red_noise_delays(k_rn, b, recipe.rn_log10_amplitude,
                             recipe.rn_gamma)
        + B.chromatic_noise_delays(k_chrom, b,
                                   recipe.chrom_log10_amplitude,
                                   recipe.chrom_gamma)
        + B.gwb_delays(k_gwb, b,
                       recipe.gwb_log10_amplitude, recipe.gwb_gamma,
                       jnp.sqrt(2.0) * jnp.eye(4, dtype=b.toas_s.dtype),
                       npts=64, howml=4.0)
    )
    assert np.array_equal(np.asarray(total), np.asarray(parts))


def test_pipeline_variance_matches_analytic():
    """Integration guard on the summed pipeline: across realizations, the
    per-pulsar mean residual variance of white+ECORR+red-noise equals the
    exact analytic sum — Var = (efac sigma)^2 + (efac equad)^2 (t2equad)
    + ecorr^2, plus sum_k prior_k for the Fourier red noise
    (sin^2+cos^2 = 1 makes the RN variance TOA-independent)."""
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.ops.fourier import fourier_frequencies, powerlaw_prior

    npsr, ntoa, nreal = 4, 1024, 512
    b = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=2, seed=5)
    recipe = B.Recipe(
        efac=jnp.full((npsr, 2), 1.2),
        log10_equad=jnp.full((npsr, 2), -6.3),
        log10_ecorr=jnp.full((npsr, 2), -6.4),
        rn_log10_amplitude=jnp.full(npsr, -13.6),
        rn_gamma=jnp.full(npsr, 3.0),
    )
    res = np.asarray(B.realize(jax.random.PRNGKey(3), b, recipe, nreal=nreal))
    meas = res.var(axis=0).mean(axis=-1)  # (Np,) mean-over-TOA variance

    efac, equad, ecorr = 1.2, 10.0**-6.3, 10.0**-6.4
    white = (efac * np.asarray(b.errors_s)) ** 2 + (efac * equad) ** 2
    freqs = np.asarray(fourier_frequencies(b.tspan_s, nmodes=30))
    prior = np.asarray(
        powerlaw_prior(
            np.repeat(freqs, 2, axis=-1),
            np.full(npsr, -13.6), np.full(npsr, 3.0), np.asarray(b.tspan_s),
        )
    )
    # prior is per COLUMN (sin and cos repeat each frequency), while
    # sin^2+cos^2 = 1 counts each frequency once: RN variance = sum/2
    want = white.mean(axis=-1) + ecorr**2 + prior.sum(axis=-1) / 2.0
    # nreal=512 with TOA-correlated RN: ~5-10% sampling scatter
    np.testing.assert_allclose(meas, want, rtol=0.12)


def test_cw_planes_api_sweep_keeps_accuracy():
    """Catalog sweeps via precomputed planes keep the f64 host accuracy
    through jit boundaries: planes are data. Pins (a) from_planes ==
    direct concrete call bitwise, (b) a jitted/vmapped sweep over
    stacked per-catalog planes == per-catalog direct calls, and (c) the
    planes precompute refuses tracers loudly."""
    from pta_replicator_tpu.batch import synthetic_batch

    batch = synthetic_batch(npsr=3, ntoa=128, nbackend=2, seed=4,
                            dtype=jnp.float32)
    ncat, ncw = 3, 5

    def catalog(i):
        r = np.random.default_rng(100 + i)
        return [
            np.arccos(r.uniform(-1, 1, ncw)), r.uniform(0, 2 * np.pi, ncw),
            10 ** r.uniform(8, 9.3, ncw), r.uniform(50, 900, ncw),
            10 ** r.uniform(-8.6, -7.8, ncw), r.uniform(0, 2 * np.pi, ncw),
            r.uniform(0, np.pi, ncw), np.arccos(r.uniform(-1, 1, ncw)),
        ]

    direct = [
        np.asarray(B.cgw_catalog_delays(batch, *catalog(i), chunk=8))
        for i in range(ncat)
    ]

    planes = [B.cw_catalog_planes_for(batch, *catalog(i)) for i in range(ncat)]
    src0, psr0, evolve0 = planes[0]
    a = np.asarray(
        B.cgw_catalog_delays_from_planes(
            batch, src0, psr0, evolve=evolve0, chunk=8
        )
    )
    assert np.array_equal(a, direct[0])  # same planes, same math

    src_stack = jnp.stack([p[0] for p in planes])
    psr_stack = jnp.stack([p[1] for p in planes])
    swept = np.asarray(
        jax.jit(
            jax.vmap(
                lambda s, p: B.cgw_catalog_delays_from_planes(
                    batch, s, p, evolve=True, chunk=8
                )
            )
        )(src_stack, psr_stack)
    )
    rms = np.sqrt(np.mean(np.stack(direct) ** 2))
    dev = np.abs(swept - np.stack(direct)).max()
    # planes pass through jit as data: only f32 re-association remains
    assert dev <= 1e-5 * rms, (dev, rms)

    with pytest.raises(TypeError, match="concrete"):
        jax.jit(lambda c: B.cw_catalog_planes_for(batch, *c))(
            [jnp.asarray(x) for x in catalog(0)]
        )


def test_chromatic_noise_scaling_and_oracle_parity():
    """Chromatic noise scales per TOA as (ref/freq)^index and the device
    op reproduces the oracle exactly under a shared coefficient stream."""
    from pta_replicator_tpu import add_chromatic_noise, load_pulsar, make_ideal
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models.red_noise import red_noise_delay

    # device: explicit eps, scaling law exact
    b = synthetic_batch(npsr=3, ntoa=256, nbackend=2, seed=6)
    eps = np.random.default_rng(0).normal(size=(3, 60))
    d2 = np.asarray(
        B.chromatic_noise_delays(
            None, b, jnp.full(3, -13.5), jnp.full(3, 3.0),
            chromatic_index=2.0, eps=jnp.asarray(eps),
        )
    )
    d4 = np.asarray(
        B.chromatic_noise_delays(
            None, b, jnp.full(3, -13.5), jnp.full(3, 3.0),
            chromatic_index=4.0, eps=jnp.asarray(eps),
        )
    )
    scale2 = (1400.0 / np.asarray(b.freqs_mhz)) ** 2
    np.testing.assert_allclose(d4, d2 * scale2, rtol=1e-10)
    # achromatic part recovered by dividing the scaling out
    achrom = np.asarray(
        B.red_noise_delays(
            None, b, jnp.full(3, -13.5), jnp.full(3, 3.0),
            eps=jnp.asarray(eps),
        )
    )
    np.testing.assert_allclose(d2, achrom * scale2, rtol=1e-10)

    # oracle: ledger + seeded draw layout; matches a hand-built delay
    psr = load_pulsar(
        "/root/reference/test_partim_small/par/JPSR00.par",
        "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim",
    )
    make_ideal(psr)
    mjd0 = psr.toas.get_mjds().copy()
    add_chromatic_noise(psr, -13.5, 3.0, chromatic_index=2.0, seed=42)
    dt = psr.added_signals_time[f"{psr.name}_chromatic_noise"]
    np.random.seed(42)
    eps_o = np.random.randn(60)
    toas_s = mjd0 * 86400.0
    want = red_noise_delay(
        toas_s, -13.5, 3.0, eps_o, nmodes=30,
        tspan_s=float(toas_s.max() - toas_s.min()),
    ) * (1400.0 / np.asarray(psr.toas.freqs_mhz)) ** 2
    np.testing.assert_allclose(dt, want, rtol=1e-12)


def test_gls_fit_subtract_matches_oracle_dense():
    """Device GLS refit (nested-Woodbury, never materializing C) must
    match the oracle's dense-covariance GLS projection on the same
    design columns, per pulsar, to float tolerance — white + per-backend
    ECORR + achromatic + chromatic red noise all in the weighting."""
    import jax.numpy as jnp

    from pta_replicator_tpu import load_pulsar, make_ideal
    from pta_replicator_tpu.batch import freeze
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.timing.fit import (
        covariance_from_recipe,
        design_tensor,
        gls_fit,
    )
    from pta_replicator_tpu.timing.components import full_design_matrix

    pardir = "/root/reference/test_partim_small/par"
    timdir = "/root/reference/test_partim_small/tim"
    names = ["JPSR00", "JPSR01"]
    psrs = []
    for n in names:
        p = load_pulsar(f"{pardir}/{n}.par",
                        f"{timdir}/fake_{n}_noiseonly.tim")
        make_ideal(p)
        psrs.append(p)
    batch = freeze(psrs, dtype=jnp.float64)
    nb = len(batch.backend_names)

    rng = np.random.default_rng(5)
    recipe = B.Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.4, (batch.npsr, nb))),
        log10_equad=jnp.asarray(rng.uniform(-6.8, -6.2, (batch.npsr, nb))),
        log10_ecorr=jnp.asarray(rng.uniform(-6.9, -6.4, (batch.npsr, nb))),
        rn_log10_amplitude=jnp.asarray(rng.uniform(-13.8, -13.2, batch.npsr)),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, batch.npsr)),
        chrom_log10_amplitude=jnp.asarray(
            rng.uniform(-13.9, -13.4, batch.npsr)),
        chrom_gamma=jnp.asarray(rng.uniform(2.5, 4.0, batch.npsr)),
        chrom_index=jnp.asarray(2.0),
        # GWB auto-term block in the weighting (VERDICT r4 weak #6) —
        # exercised through the same device-vs-dense-oracle comparison
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
    )

    delays = jnp.asarray(rng.standard_normal(batch.toas_s.shape) * 1e-6)
    delays = delays * batch.mask
    design, _names = design_tensor(psrs, ntoa_max=batch.ntoa_max)

    post = np.asarray(
        B.gls_fit_subtract(delays, batch, design, recipe)
    )
    dev_sig = np.asarray(
        B.gls_fit_uncertainties(batch, design, recipe)
    )

    # oracle, per pulsar, dense C (quantize epochs must match the
    # batch's: same coarsegrain default)
    for i, psr in enumerate(psrs):
        n = psr.toas.ntoas
        C = covariance_from_recipe(
            psr, recipe, psr_index=i, backend_names=batch.backend_names,
        )
        M, _ = full_design_matrix(
            psr.par, psr.toas.get_mjds(), freqs_mhz=psr.toas.freqs_mhz,
            f0=psr.model.f0, flags=psr.toas.flags,
        )
        r = np.asarray(delays[i][:n], dtype=np.float64)
        _, ref_post, ref_cov = gls_fit(r, C, M, return_cov=True)
        num = np.sqrt(np.mean((post[i][:n] - ref_post) ** 2))
        den = np.sqrt(np.mean(ref_post**2))
        assert num / den < 1e-6, (i, num / den)
        # device (M^T C^-1 M)^-1 sigmas match the dense-oracle ones
        ref_sig = np.sqrt(np.clip(np.diag(ref_cov), 0.0, None))
        kk = M.shape[1]
        np.testing.assert_allclose(
            dev_sig[i][:kk], ref_sig, rtol=1e-6
        )
        # padding columns report exactly 0
        assert np.all(dev_sig[i][kk:] == 0.0)


def test_gwb_auto_prior_powerlaw_equivalence():
    """The GWB auto-term prior hc^2/(12 pi^2 f^3 T) must reduce exactly
    to the enterprise power-law prior at (A_gwb, gamma_gwb) for a
    power-law spectrum — the identity the GLS block is built on."""
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.ops.fourier import (
        fourier_frequencies,
        powerlaw_prior,
    )

    b = synthetic_batch(npsr=2, ntoa=64, nbackend=2, seed=1,
                        dtype=jnp.float64)
    A, gam = -14.2, 13.0 / 3.0
    white = B.Recipe(efac=jnp.asarray(1.0))
    gwb = B.Recipe(
        efac=jnp.asarray(1.0),
        gwb_log10_amplitude=jnp.asarray(A),
        gwb_gamma=jnp.asarray(gam),
    )
    _, _, U0, phi0 = B.gls_noise_model(b, white)
    _, _, U, phi = B.gls_noise_model(b, gwb)
    assert U0 is None and U is not None
    T = float(np.asarray(b.tspan_s[0]))
    f = np.asarray(fourier_frequencies(T, nmodes=30))
    # hc = A (f/f1yr)^alpha with the reference's f1yr = 1/3.16e7 (the
    # convention the injection op uses — NOT the exact YEAR_IN_SEC of
    # powerlaw_prior, a deliberate 0.2% parity choice), so the block
    # must be built from the same hc the synthesis injects
    hc = 10.0**A * (f * 3.16e7) ** (-0.5 * (gam - 3.0))
    want = np.repeat(hc**2 / (12.0 * np.pi**2 * f**3 * T), 2)
    np.testing.assert_allclose(np.asarray(phi[0]), want, rtol=1e-10)
    # and agrees with the enterprise powerlaw prior to the year-convention
    # difference (~0.2% at gamma = 13/3)
    ent = np.asarray(powerlaw_prior(np.repeat(f, 2), A, gam, T))
    np.testing.assert_allclose(np.asarray(phi[0]), ent, rtol=5e-3)


def test_gwb_auto_prior_user_spectrum():
    """The GLS GWB block must follow a user-supplied hc(f) — including
    the flat endpoint clamp — not just the power law."""
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.ops.fourier import fourier_frequencies

    b = synthetic_batch(npsr=2, ntoa=64, nbackend=2, seed=1,
                        dtype=jnp.float64)
    T = float(np.asarray(b.tspan_s[0]))
    uf = np.logspace(-8.6, -7.6, 16)
    uh = 2e-15 * (uf / 1e-8) ** (-2.0 / 3.0)
    rec = B.Recipe(
        efac=jnp.asarray(1.0),
        gwb_user_spectrum=jnp.asarray(np.column_stack([uf, uh])),
    )
    _, _, U, phi = B.gls_noise_model(b, rec)
    assert U is not None and bool(jnp.all(jnp.isfinite(phi)))
    f = np.asarray(fourier_frequencies(T, nmodes=30))
    # inside the node range the prior tracks the user power law
    inside = (f >= uf[0]) & (f <= uf[-1])
    hc = 2e-15 * (f / 1e-8) ** (-2.0 / 3.0)
    want = hc**2 / (12.0 * np.pi**2 * f**3 * T)
    got = np.asarray(phi[0])[0::2]  # sin coefficients, one per freq
    np.testing.assert_allclose(got[inside], want[inside], rtol=1e-6)
    # below the first node: flat hc clamp (uh[0])
    below = f < uf[0]
    if below.any():
        want_lo = uh[0] ** 2 / (12.0 * np.pi**2 * f[below] ** 3 * T)
        np.testing.assert_allclose(got[below], want_lo, rtol=1e-6)


def test_gwb_auto_term_variance_calibration():
    """VERDICT r4 weak #6 done-condition: the GWB block's per-coefficient
    prior must match the MEASURED coefficient scatter of the actual GWB
    synthesis op. 200 oracle realizations on a real-fixture pulsar,
    jointly fit (quadratic + 30-mode Fourier, column-normalized); the
    empirical variance of each Fourier coefficient must match
    hc^2/(12 pi^2 f^3 T) — i.e. powerlaw_prior(A, gamma) — mode by mode.
    (Raw projection without the quadratic columns is dominated by the
    synthesis grid's sub-1/T leakage, which the timing fit absorbs; the
    calibration run measured per-mode ratios 0.92-1.10, median 1.00.)"""
    import copy

    import pta_replicator_tpu as ptr
    from pta_replicator_tpu.ops.fourier import (
        fourier_basis,
        fourier_frequencies,
        powerlaw_prior,
    )

    base = ptr.load_from_directories(
        "/root/reference/test_partim_small/par",
        "/root/reference/test_partim_small/tim",
    )
    for q in base:
        ptr.make_ideal(q)
    toas_s = base[0].toas.get_mjds().astype(np.float64) * 86400.0
    T = float(toas_s.max() - toas_s.min())
    f = fourier_frequencies(T, nmodes=30)
    F = fourier_basis(toas_s, f)
    t = toas_s - toas_s.mean()
    M = np.concatenate(
        [np.stack([np.ones_like(t), t, t**2], axis=-1), F], axis=-1
    )
    norms = np.sqrt((M**2).sum(axis=0))
    Mn = M / norms

    A, gam = 1e-14, 13.0 / 3.0
    nreal = 200
    coefs = np.zeros((nreal, F.shape[1]))
    for i in range(nreal):
        psrs = copy.deepcopy(base[:1])  # only pulsar 0 is used
        ptr.add_gwb(psrs, np.log10(A), gam, seed=5000 + i)
        r = psrs[0].residuals.resids_value
        c, *_ = np.linalg.lstsq(Mn, r, rcond=None)
        coefs[i] = (c / norms)[3:]
    emp = coefs.var(axis=0)
    prior = np.asarray(powerlaw_prior(np.repeat(f, 2), np.log10(A),
                                      gam, T))
    ratio = (0.5 * (emp[0::2] + emp[1::2])
             / (0.5 * (prior[0::2] + prior[1::2])))
    # 200 samples -> var-of-variance ~ sqrt(2/200) ~ 10% per mode
    assert 0.9 < np.median(ratio) < 1.1, np.median(ratio)
    assert np.all((ratio > 0.6) & (ratio < 1.6)), ratio


def test_gls_zero_power_modes_inert():
    """A pulsar whose red noise is off (log10_A = -inf -> phi = 0) must
    get EXACTLY the white-only GLS weighting: the phi->0 limit is an
    infinite-precision (1/phi) prior, i.e. the mode contributes nothing.
    Regression for the phi_safe=1.0 substitution, which handed such a
    pulsar a spurious unit-variance (1 s^2!) red-noise block."""
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B

    b = synthetic_batch(npsr=2, ntoa=128, nbackend=2, seed=3,
                        dtype=jnp.float64)
    rng = np.random.default_rng(11)
    delays = jnp.asarray(rng.standard_normal(b.toas_s.shape) * 1e-6)
    delays = delays * b.mask
    t = b.toas_s - jnp.mean(b.toas_s, axis=-1, keepdims=True)
    design = jnp.stack(
        [jnp.ones_like(t), t, t**2], axis=-1
    ) * b.mask[..., None]

    mixed = B.Recipe(
        efac=jnp.asarray(1.1),
        rn_log10_amplitude=jnp.asarray([-jnp.inf, -13.5]),
        rn_gamma=jnp.asarray([4.33, 4.33]),
    )
    white_only = B.Recipe(efac=jnp.asarray(1.1))

    post_mixed = np.asarray(B.gls_fit_subtract(delays, b, design, mixed))
    post_white = np.asarray(
        B.gls_fit_subtract(delays, b, design, white_only)
    )
    # pulsar 0 (red noise off) must match the white-only weighting
    np.testing.assert_allclose(post_mixed[0], post_white[0],
                               rtol=1e-12, atol=1e-18)
    # pulsar 1 (red noise on) must NOT — the block must actually engage
    assert np.max(np.abs(post_mixed[1] - post_white[1])) > 0.0


def test_backend_table_width_validated():
    """A per-backend table narrower than the batch's backend vocabulary
    must raise at trace time — the out-of-bounds gather would otherwise
    fill with NaN and silently poison every realization (found by the
    f32 GLS test with a mis-sized fixture table)."""
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B

    b = synthetic_batch(npsr=3, ntoa=64, nbackend=3, seed=0)
    key = jax.random.PRNGKey(0)
    bad = jnp.ones((3, 2))  # 2 columns for a 3-backend batch
    with pytest.raises(ValueError, match="backend column"):
        B.white_noise_delays(key, b, efac=bad)
    with pytest.raises(ValueError, match="backend column"):
        B.jitter_delays(key, b, log10_ecorr=jnp.full((3, 2), -6.5))
    with pytest.raises(ValueError, match="backend column"):
        B.gls_noise_model(b, B.Recipe(efac=bad))
