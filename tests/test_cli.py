"""CLI runner: par/tim + JSON recipe -> realizations npz."""
import json

import numpy as np
import pytest

from pta_replicator_tpu.__main__ import main


def test_cli_info_and_realize(tmp_path, partim_small, capsys):
    pardir, timdir = partim_small
    main(["info", "--pardir", pardir, "--timdir", timdir])
    info = json.loads(capsys.readouterr().out.strip())
    assert info["npsr"] == 3 and info["ntoa_max"] == 122

    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({
        "efac": 1.1, "rn_log10_amplitude": -14.0, "rn_gamma": 4.33,
        "gwb_log10_amplitude": -14.0, "gwb_gamma": 4.33,
        "gwb_npts": 100, "gwb_howml": 4.0, "orf": "hd",
    }))
    out = tmp_path / "res.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "8", "--out", str(out),
          "--fit"])
    report = json.loads(capsys.readouterr().out.strip())
    assert report["shape"] == [8, 3, 122]
    with np.load(out) as z:
        assert z["residuals"].shape == (8, 3, 122)
        assert np.isfinite(z["residuals"]).all()
        assert list(z["names"]) == ["JPSR00", "JPSR01", "JPSR02"]


def test_cli_checkpointed_and_sharded(tmp_path, partim_small, capsys):
    pardir, timdir = partim_small
    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({"efac": 1.0}))
    out = tmp_path / "res.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "8", "--chunk", "4",
          "--checkpoint", str(tmp_path / "ck.npz"), "--out", str(out)])
    json.loads(capsys.readouterr().out.strip())
    out2 = tmp_path / "res2.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "8", "--sharded",
          "--out", str(out2)])
    with np.load(out) as a, np.load(out2) as b:
        assert a["residuals"].shape == b["residuals"].shape == (8, 3, 122)


def test_cli_rejects_unknown_recipe_key(tmp_path, partim_small):
    pardir, timdir = partim_small
    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({"efacc": 1.0}))
    with pytest.raises(SystemExit, match="efacc"):
        main(["realize", "--pardir", pardir, "--timdir", timdir,
              "--recipe", str(recipe), "--nreal", "4",
              "--out", str(tmp_path / "x.npz")])


def test_cli_full_fit(tmp_path, partim_small, capsys):
    """--full-fit builds the per-pulsar design tensor from the loaded
    pars and runs the full-model per-realization refit (implies --fit)."""
    pardir, timdir = partim_small
    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({"efac": 1.1, "orf": "none",
                                  "gwb_log10_amplitude": -14.0,
                                  "gwb_gamma": 4.33,
                                  "gwb_npts": 100, "gwb_howml": 4.0}))
    out = tmp_path / "res.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "4", "--out", str(out),
          "--full-fit"])
    report = json.loads(capsys.readouterr().out.strip())
    assert report["shape"] == [4, 3, 122]
    with np.load(out) as z:
        full = z["residuals"]
    assert np.isfinite(full).all()

    # --full-fit must actually differ from the quadratic --fit proxy AND
    # absorb at least as much power (more columns, same realizations) —
    # a silent fallback to --fit would fail both checks
    out2 = tmp_path / "res_quad.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "4", "--out", str(out2),
          "--fit"])
    json.loads(capsys.readouterr().out.strip())
    with np.load(out2) as z:
        quad = z["residuals"]
    assert not np.allclose(full, quad, rtol=1e-6, atol=0.0)
    rms = lambda x: float(np.sqrt(np.mean(x**2)))
    assert rms(full) <= rms(quad) * (1.0 + 1e-9)


def test_cli_write_partim(tmp_path, partim_small, capsys):
    """--write-partim materializes loadable per-realization datasets."""
    from pta_replicator_tpu import load_pulsar

    pardir, timdir = partim_small
    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({"efac": 1.2}))
    out = tmp_path / "res.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "4", "--out", str(out),
          "--write-partim", str(tmp_path / "ds"), "--write-max", "2"])
    report = json.loads(capsys.readouterr().out.strip())
    assert report["partim_dirs"] == 2
    psr = load_pulsar(str(tmp_path / "ds" / "real00001" / "JPSR00.par"),
                      str(tmp_path / "ds" / "real00001" / "JPSR00.tim"))
    assert psr.toas.ntoas == 122
    # a white-noise-only dataset reloads with ~efac*sigma scatter
    rms = float(np.sqrt(np.mean(psr.residuals.resids_value ** 2)))
    assert 0.2e-6 < rms < 5e-6

    # checkpointed sweeps consume a different key stream (fold_in per
    # chunk): the written dataset r must still carry residual-cube row
    # r's delays — compare the reloaded TOA shifts, residualized, to the
    # cube (no-fit: cube rows are residualize(delays))
    out_ck = tmp_path / "res_ck.npz"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "4", "--chunk", "2",
          "--checkpoint", str(tmp_path / "ck.npz"), "--out", str(out_ck),
          "--write-partim", str(tmp_path / "ds_ck"), "--write-max", "3"])
    json.loads(capsys.readouterr().out.strip())
    import pta_replicator_tpu as ptr

    template = ptr.load_pulsar(f"{pardir}/JPSR00.par",
                               f"{timdir}/fake_JPSR00_noiseonly.tim")
    ptr.make_ideal(template)  # the CLI injects into make_ideal'd TOAs
    with np.load(out_ck) as z:
        cube = z["residuals"]
    r = 2  # falls in the second sweep chunk
    re = load_pulsar(str(tmp_path / "ds_ck" / f"real{r:05d}" / "JPSR00.par"),
                     str(tmp_path / "ds_ck" / f"real{r:05d}" / "JPSR00.tim"))
    shift_s = np.asarray(
        (re.toas.mjd - template.toas.mjd) * np.longdouble(86400.0), np.float64
    )
    w = 1.0 / template.toas.errors_s**2
    shift_res = shift_s - np.sum(w * shift_s) / np.sum(w)
    np.testing.assert_allclose(shift_res, cube[r, 0], atol=5e-9, rtol=0)


def test_cli_gls_fit(tmp_path, partim_small, capsys):
    """--gls-fit runs the full-model refit weighted by the recipe noise
    model end-to-end through the CLI."""
    pardir, timdir = partim_small
    recipe = tmp_path / "r.json"
    recipe.write_text(json.dumps({
        "efac": 1.1, "log10_equad": -6.5, "log10_ecorr": -6.7,
        "rn_log10_amplitude": -13.5, "rn_gamma": 3.5,
    }))
    out = tmp_path / "o.npz"
    main([
        "realize", "--pardir", pardir, "--timdir", timdir,
        "--recipe", str(recipe), "--nreal", "4", "--out", str(out),
        "--gls-fit", "--seed", "3",
    ])
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["shape"][0] == 4
    with np.load(out) as z:
        res = z["residuals"]
    assert np.isfinite(res).all()
    assert res.std() > 0
