"""covariance/: every CovOp against its dense f64 oracle, blocked
kernels (incl. Pallas-interpret bit-identity), the fold_in stream
contract of the correlated-noise injection, the covariance-aware
GLS/likelihood wiring, the scenario section, and the
inject->fit round trip. Fixture-free (synthetic batches), f64
(conftest enables x64)."""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.covariance import (
    BandedCov,
    LowRankCov,
    banded_from_times,
    dense_from_times,
    dense_noise_covariance,
    kron_time_channel,
)
from pta_replicator_tpu.covariance import kernels as K
from pta_replicator_tpu.covariance.structure import (
    COV_STREAM_FOLD,
    recipe_cov_s2,
)
from pta_replicator_tpu.likelihood import gp
from pta_replicator_tpu.models.batched import (
    Recipe,
    gls_fit_subtract,
    realization_delays,
    realize,
)

NPSR, NT = 4, 128


@pytest.fixture(scope="module")
def batch():
    return synthetic_batch(npsr=NPSR, ntoa=NT, nbackend=2, seed=1,
                           dtype=jnp.float64)


@pytest.fixture(scope="module")
def masked_batch(batch):
    """A batch with a padding-style masked tail on pulsar 0."""
    mask = np.asarray(batch.mask).copy()
    mask[0, -9:] = 0.0
    return dataclasses.replace(
        batch,
        mask=jnp.asarray(mask, batch.mask.dtype),
        ntoas=jnp.asarray(mask.sum(axis=-1), batch.ntoas.dtype),
    )


def _ops(batch):
    t = np.asarray(batch.toas_s)
    m = np.asarray(batch.mask)
    banded = banded_from_times(t, m, rho=0.6, corr_s=40 * 86400.0,
                               block=16, dtype=jnp.float64)
    kron = kron_time_channel(t, channels=4, time_ell_s=20 * 86400.0,
                             chan_rho=0.8, dtype=jnp.float64)
    dense = dense_from_times(t, m, corr_s=60 * 86400.0,
                             dtype=jnp.float64)
    rng = np.random.default_rng(2)
    U = rng.standard_normal((NPSR, NT, 5)) * 0.3 * m[:, :, None]
    lowrank = LowRankCov(
        base=banded, U=jnp.asarray(U),
        phi=jnp.asarray(rng.uniform(0.5, 1.5, (NPSR, 5))),
    )
    return {"banded": banded, "kron": kron, "dense": dense,
            "lowrank": lowrank}


# ------------------------------------------------- CovOp vs oracle

@pytest.mark.parametrize("kind", ["banded", "kron", "dense", "lowrank"])
def test_covop_matches_dense_oracle(masked_batch, batch, kind):
    """The acceptance bar: matvec/solve/logdet/sample of every CovOp
    within 1e-8 relative of its numpy-f64 dense oracle (per-pulsar s2
    too). kron requires the full grid; the others run masked."""
    b = batch if kind == "kron" else masked_batch
    op = _ops(b)[kind]
    C = op.dense(pad_identity=True)
    Cpure = op.dense(pad_identity=False)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((NPSR, NT))
    s2 = rng.uniform(0.5, 2.0, NPSR)

    mv = np.asarray(op.matvec(jnp.asarray(x), s2=jnp.asarray(s2)))
    mv_o = np.einsum("pij,pj->pi", Cpure, x) * s2[:, None]
    assert np.max(np.abs(mv - mv_o)) <= 1e-8 * np.max(np.abs(mv_o))

    z = np.asarray(op.solve(jnp.asarray(x), s2=jnp.asarray(s2)))
    z_o = np.stack([np.linalg.solve(s2[p] * C[p], x[p])
                    for p in range(NPSR)])
    assert np.max(np.abs(z - z_o)) <= 1e-8 * np.max(np.abs(z_o))

    ld = np.asarray(op.logdet(s2=jnp.asarray(s2)))
    ld_o = np.array([np.linalg.slogdet(C[p])[1] for p in range(NPSR)])
    ld_o = ld_o + np.asarray(op.nvalid) * np.log(s2)
    assert np.max(np.abs(ld - ld_o)) <= 1e-8 * np.max(np.abs(ld_o))

    key = jax.random.PRNGKey(5)
    smp = np.asarray(op.sample(key, s2=jnp.asarray(s2)))
    mask = np.asarray(b.mask)
    if kind == "lowrank":
        k_base, k_lr = jax.random.split(key, 2)
        zb = np.asarray(jax.random.normal(k_base, (NPSR, NT),
                                          jnp.float64))
        zl = np.asarray(jax.random.normal(
            k_lr, (NPSR, op.phi.shape[1]), jnp.float64
        ))
        Lb = np.linalg.cholesky(op.base.dense(pad_identity=True))
        smp_o = (
            np.einsum("pij,pj->pi", Lb, zb) * mask
            + np.einsum("pnr,pr->pn", np.asarray(op.U),
                        np.sqrt(np.asarray(op.phi)) * zl)
        ) * np.sqrt(s2)[:, None]
    else:
        zf = np.asarray(jax.random.normal(key, (NPSR, NT), jnp.float64))
        L = np.linalg.cholesky(C)
        smp_o = np.einsum("pij,pj->pi", L, zf) \
            * np.sqrt(s2)[:, None] * mask
    assert np.max(np.abs(smp - smp_o)) <= 1e-8 * np.max(np.abs(smp_o))


def test_sample_rows_window(masked_batch):
    """``rows=(npsr_global, start)`` draws an exact row window of the
    global stream: a CovOp restricted to rows [1, 3) sampling with
    rows= matches the full op's sample rows 1:3 bitwise."""
    op = _ops(masked_batch)["banded"]
    key = jax.random.PRNGKey(9)
    full = op.sample(key)

    def window(leaf):
        if hasattr(leaf, "shape") and leaf.ndim >= 1 \
                and leaf.shape[0] == NPSR:
            return leaf[1:3]
        return leaf

    local = jax.tree_util.tree_map(window, op)
    win = local.sample(key, rows=(NPSR, 1))
    assert bool(jnp.all(win == full[1:3]))


# --------------------------------------------------- blocked kernels

@pytest.mark.parametrize("dtype", [jnp.float64, jnp.float32])
def test_blocked_cholesky_pallas_interpret_bit_identical(dtype):
    """The Pallas SYRK tile kernel (interpret mode) and the tiled-XLA
    fallback run the SAME per-tile op sequence — bit-identical factors
    on CPU, at both precisions (the one-op-sequence discipline of
    ops/pallas_cw.py)."""
    rng = np.random.default_rng(7)
    A = rng.standard_normal((2, 160, 160))
    A = A @ np.swapaxes(A, -1, -2) + 160 * np.eye(160)
    A = jnp.asarray(A, dtype)
    Lx = K.blocked_cholesky(A, block=32, backend="xla")
    Lp = K.blocked_cholesky(A, block=32, backend="pallas_interpret")
    assert bool(jnp.all(Lx == Lp))


def test_blocked_cholesky_matches_lapack_with_padding():
    """Blocked factorization == LAPACK on a non-multiple-of-block size
    (the identity-padded grid must not leak into the factor)."""
    rng = np.random.default_rng(8)
    n = 130  # not a multiple of the block
    A = rng.standard_normal((3, n, n))
    A = A @ np.swapaxes(A, -1, -2) + n * np.eye(n)
    A = jnp.asarray(A)
    L = K.blocked_cholesky(A, block=32, backend="xla")
    assert np.allclose(np.asarray(L), np.linalg.cholesky(np.asarray(A)),
                       atol=1e-10)


def test_block_tridiag_kernels_vs_dense(masked_batch):
    """Factor/solve/logdet of the block-tridiagonal kernels against a
    dense factorization of the same matrix (the structured fast lane
    the banded combined solver stands on)."""
    op = _ops(masked_batch)["banded"]
    pad = jnp.einsum(
        "ij,pkj->pkij", jnp.eye(op.block, dtype=jnp.float64),
        1.0 - op.valid.reshape(NPSR, -1, op.block),
    )
    Ld, M = K.block_tridiag_cholesky(op.D + pad, op.E)
    C = op.dense(pad_identity=True)
    ld_o = np.array([np.linalg.slogdet(C[p])[1] for p in range(NPSR)])
    assert np.allclose(np.asarray(K.block_tridiag_logdet(Ld)), ld_o,
                       atol=1e-9)
    rng = np.random.default_rng(4)
    x = rng.standard_normal((NPSR, NT, 2))
    xg = jnp.asarray(x).reshape(NPSR, -1, op.block, 2)
    z = np.asarray(K.block_tridiag_solve(Ld, M, xg)).reshape(
        NPSR, NT, 2
    )
    z_o = np.stack([np.linalg.solve(C[p], x[p]) for p in range(NPSR)])
    assert np.max(np.abs(z - z_o)) <= 1e-9 * np.max(np.abs(z_o))


# ------------------------------------------------ injection wiring

def _recipe(batch, cov=None, ls=-6.3, **kw):
    rng = np.random.default_rng(0)
    base = dict(
        efac=jnp.asarray(rng.uniform(0.9, 1.2, (NPSR, 2))),
        rn_log10_amplitude=jnp.asarray(
            rng.uniform(-13.6, -13.2, NPSR)
        ),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, NPSR)),
        rn_nmodes=8,
    )
    base.update(kw)
    if cov is not None:
        base["noise_cov"] = cov
        base["cov_log10_sigma"] = jnp.asarray(ls)
    return Recipe(**base)


def test_fold_in_stream_independence(masked_batch):
    """Enabling the correlated-noise family leaves every other
    family's draws bit-identical: the cov sample rides
    fold_in(key, COV_STREAM_FOLD), never a widened split."""
    cov = _ops(masked_batch)["banded"]
    rec0 = _recipe(masked_batch)
    rec1 = _recipe(masked_batch, cov=cov)
    key = jax.random.PRNGKey(21)
    d0 = realization_delays(key, masked_batch, rec0)
    d1 = realization_delays(key, masked_batch, rec1)
    smp = rec1.noise_cov.sample(
        jax.random.fold_in(key, COV_STREAM_FOLD),
        s2=recipe_cov_s2(rec1, jnp.float64),
    ) * masked_batch.mask
    assert bool(jnp.all(d0 + smp == d1))
    assert not bool(jnp.all(smp == 0.0))


def test_realize_engine_with_covop(masked_batch):
    """The jitted production engine accepts a Recipe with a CovOp
    pytree riding inside (compile + run, finite output)."""
    cov = _ops(masked_batch)["banded"]
    rec = _recipe(masked_batch, cov=cov)
    out = realize(jax.random.PRNGKey(2), masked_batch, rec, nreal=3,
                  fit=False)
    out = np.asarray(out)
    assert out.shape == (3, NPSR, NT)
    assert np.all(np.isfinite(out))


# --------------------------------------- likelihood / GLS wiring

def _residuals(batch, seed=5):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.standard_normal(np.asarray(batch.toas_s).shape) * 1e-6
    ) * batch.mask


def _design(batch):
    t = np.asarray(batch.toas_s)
    scale = np.asarray(batch.tspan_s)[:, None]
    cols = [np.ones_like(t), t / scale, (t / scale) ** 2,
            np.zeros_like(t)]  # one padding column
    return jnp.asarray(np.stack(cols, axis=-1))


@pytest.mark.parametrize("kind,ecorr", [
    ("banded", False),   # structured block-tridiagonal fast lane
    ("banded", True),    # banded + ECORR -> dense fallback
    ("kron", False),     # Kronecker extra -> dense fallback
    ("dense", False),
    ("lowrank", False),
])
def test_likelihood_with_cov_matches_dense_oracle(
    batch, masked_batch, kind, ecorr
):
    """The covariance-aware GP likelihood (both solver lanes) against
    the shared dense f64 oracle, timing design marginalized."""
    b = batch if kind == "kron" else masked_batch
    kw = {"log10_ecorr": jnp.asarray(-6.7)} if ecorr else {}
    rec = _recipe(b, cov=_ops(b)[kind], **kw)
    res = _residuals(b)
    design = _design(b)
    ll = float(gp.loglikelihood(res, b, rec, design=design))
    ll_d = gp.dense_loglikelihood(np.asarray(res), b, rec,
                                  design=np.asarray(design))
    assert abs(ll - ll_d) <= 1e-8 * abs(ll_d)


def test_reduced_gp_with_cov_matches_direct(masked_batch):
    """ReducedGP retains the CovOp + frozen amplitude: its projected
    fast-path evaluation equals the direct covariance-aware
    loglikelihood."""
    b = masked_batch
    rec = _recipe(b, cov=_ops(b)["banded"])
    res = _residuals(b)
    design = _design(b)
    red = gp.ReducedGP.build(b, rec, design=design)
    proj = red.project(res, b)
    phi = gp.phi_for_recipe(b, rec)
    ll_fast = float(red.loglikelihood(proj, phi))
    ll_direct = float(gp.loglikelihood(res, b, rec, design=design))
    assert abs(ll_fast - ll_direct) <= 1e-9 * abs(ll_direct)


def test_gls_fit_subtract_cov_aware_matches_oracle(masked_batch):
    """The batched GLS refit weighted by the full covariance (incl.
    the structured block) against the numpy GLS on the shared dense
    assembly — the covariance-aware GLS path."""
    from pta_replicator_tpu.timing.fit import gls_fit

    b = masked_batch
    rec = _recipe(b, cov=_ops(b)["banded"])
    res = _residuals(b)
    design = _design(b)
    sub = np.asarray(gls_fit_subtract(res, b, design, rec))
    C_all = dense_noise_covariance(b, rec)
    mask = np.asarray(b.mask)
    for p in range(NPSR):
        idx = np.nonzero(mask[p] > 0)[0]
        M = np.asarray(design)[p][idx][:, :3]  # drop padding column
        _p, post = gls_fit(np.asarray(res)[p, idx],
                           C_all[p][np.ix_(idx, idx)], M)
        assert np.allclose(sub[p, idx], post, atol=1e-12)


def test_dense_assembler_is_shared(masked_batch):
    """dense_loglikelihood prices exactly the assembler's C: zeroing
    the assembler-visible cov amplitude must reproduce the cov-free
    oracle (the can't-disagree-about-C satellite)."""
    b = masked_batch
    rec0 = _recipe(b)
    rec1 = _recipe(b, cov=_ops(b)["banded"], ls=-20.0)
    res = np.asarray(_residuals(b))
    # at a vanishing amplitude the structured block contributes ~0
    assert abs(
        gp.dense_loglikelihood(res, b, rec1)
        - gp.dense_loglikelihood(res, b, rec0)
    ) < 1e-6


# ------------------------------------------------ recipe validation

def test_recipe_rejects_orphan_amplitude():
    with pytest.raises(ValueError, match="cov_log10_sigma"):
        Recipe(efac=jnp.asarray(1.0),
               cov_log10_sigma=jnp.asarray(-6.5))


def test_recipe_rejects_non_covop():
    with pytest.raises(ValueError, match="noise_cov"):
        Recipe(efac=jnp.asarray(1.0), noise_cov=object())


# ------------------------------------------------- scenario section

def test_scenario_covariance_validation_errors():
    from pta_replicator_tpu.scenarios.spec import ScenarioSpec, SpecError

    base = {"array": {"npsr": 2, "ntoa": 64},
            "white": {"efac": 1.1}}

    def spec(cov):
        return ScenarioSpec.from_dict({**base, "covariance": cov})

    with pytest.raises(SpecError, match="covariance.kind"):
        spec({"kind": "circulant", "log10_sigma": -6.5}).validate()
    with pytest.raises(SpecError, match="log10_sigma"):
        spec({"kind": "banded"}).validate()
    with pytest.raises(SpecError, match="covariance.channels"):
        spec({"kind": "kron", "log10_sigma": -6.5,
              "channels": 3}).validate()
    with pytest.raises(SpecError, match="do not apply"):
        spec({"kind": "banded", "log10_sigma": -6.5,
              "chan_rho": 0.5}).validate()
    with pytest.raises(SpecError, match="solar_wind"):
        spec({"preset": "solar_wind", "kind": "banded",
              "log10_sigma": -6.5}).validate()
    # the divisibility contract must catch the preset's DEFAULT
    # channels too — a named SpecError at validate time, never a raw
    # ValueError inside compile
    bad_grid = ScenarioSpec.from_dict({
        "array": {"npsr": 2, "ntoa": 250}, "white": {"efac": 1.1},
        "covariance": {"preset": "solar_wind"},
    })
    with pytest.raises(SpecError, match="covariance.channels"):
        bad_grid.validate()
    # valid forms
    spec({"kind": "banded", "log10_sigma": -6.5, "rho": 0.4}).validate()
    spec({"preset": "solar_wind"}).validate()


def test_kron_builder_rejects_masked_grid(masked_batch):
    """The Kronecker structure has no padding escape hatch: the
    builder refuses a ragged mask instead of silently cross-coupling
    masked TOAs into the priced C0."""
    with pytest.raises(ValueError, match="FULL TOA grid"):
        kron_time_channel(
            np.asarray(masked_batch.toas_s), channels=4,
            time_ell_s=20 * 86400.0, chan_rho=0.8,
            mask=np.asarray(masked_batch.mask),
        )


def test_oracle_gls_covariance_requires_psr_index(masked_batch):
    """covariance_from_recipe resolves the per-pulsar noise_cov block
    exactly, never by defaulting: no psr_index on a multi-pulsar block
    raises (the same contract as its per-pulsar parameter rows)."""
    from pta_replicator_tpu.timing.fit import covariance_from_recipe

    # scalar white params: the ONLY per-pulsar leaf is the CovOp, so
    # the raise below must come from the noise_cov resolution itself
    rec = Recipe(efac=jnp.asarray(1.1),
                 noise_cov=_ops(masked_batch)["banded"],
                 cov_log10_sigma=jnp.asarray(-6.4))

    class _Toas:
        def get_mjds(self):
            return np.linspace(50000.0, 55000.0, 32)

        errors_s = np.full(32, 1e-6)
        freqs_mhz = np.full(32, 1400.0)

    class _Psr:
        toas = _Toas()

    with pytest.raises(ValueError, match="psr_index"):
        covariance_from_recipe(_Psr(), rec)


@pytest.mark.parametrize("cov,token", [
    ({"kind": "banded", "log10_sigma": -6.5, "rho": 0.5,
      "corr_days": 20.0, "block": 8}, "cov_banded"),
    ({"preset": "solar_wind", "log10_sigma": -6.6}, "cov_kron"),
    ({"kind": "dense", "log10_sigma": -6.5, "corr_days": 30.0},
     "cov_dense"),
])
def test_scenario_covariance_compiles_and_agrees(cov, token):
    """A covariance-section spec compiles to a Recipe carrying the
    CovOp + amplitude, claims the right coverage token, and passes the
    batched-vs-oracle differential."""
    from pta_replicator_tpu.scenarios import compile_spec
    from pta_replicator_tpu.scenarios.fuzz import run_scenario
    from pta_replicator_tpu.scenarios.spec import ScenarioSpec

    spec = ScenarioSpec.from_dict({
        "name": "cov-case", "seed": 7,
        "array": {"npsr": 2, "ntoa": 64, "nbackend": 1},
        "white": {"efac": 1.1},
        "covariance": cov,
    }).validate()
    compiled = compile_spec(spec)
    assert compiled.recipe.noise_cov is not None
    assert compiled.recipe.cov_log10_sigma is not None
    assert token in compiled.families
    res = run_scenario(compiled)
    assert res.agree, res.verdicts


# ----------------------------------------------------- round trip

@pytest.mark.slow
def test_inject_fit_round_trip(masked_batch):
    """Inject correlated noise through the production engine, recover
    the planted amplitude with map_fit under the covariance-aware
    likelihood, within 3 Fisher sigma (the bench round-trip's gate,
    smaller shape)."""
    from pta_replicator_tpu.likelihood.infer import map_fit

    b = masked_batch
    truth = -6.3
    rec = _recipe(b, cov=_ops(b)["banded"], ls=truth)
    res = np.asarray(realize(jax.random.PRNGKey(13), b, rec, nreal=1,
                             fit=False))[0]
    design = jnp.asarray(
        np.ones(np.asarray(b.toas_s).shape)[..., None]
    )  # realize() mean-subtracts; marginalize the offset to match
    fit = map_fit(jnp.asarray(res), b, rec,
                  {"cov_log10_sigma": truth + 0.3}, design=design)
    assert fit.converged
    z = (fit.x[0] - truth) / fit.sigma[0]
    assert np.isfinite(z) and abs(z) <= 3.0


def test_eager_helpers_emit_telemetry(masked_batch):
    """solve_eager/sample_eager wrap the cov_solve/cov_sample spans and
    bump the cov.{solves,blocked_fraction} metrics."""
    from pta_replicator_tpu.obs import REGISTRY, names

    op = _ops(masked_batch)["banded"]
    x = _residuals(masked_batch)
    before = K._SOLVE_TALLY["total"]
    out = K.solve_eager(op, x)
    smp = K.sample_eager(op, jax.random.PRNGKey(0))
    assert out.shape == smp.shape == x.shape
    assert K._SOLVE_TALLY["total"] == before + 1
    snap = REGISTRY.to_json()
    assert names.COV_SOLVES in snap
    assert names.COV_BLOCKED_FRACTION in snap
