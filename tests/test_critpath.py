"""Critical-path attribution (obs/critpath.py) + cross-round
performance ledger (obs/ledger.py).

The acceptance path is a REAL pipelined mesh sweep (depth 2, the
conftest-forced 8-virtual-device CPU mesh): the analyzer reconstructs
the per-chunk span DAG from the capture, the decomposition closes
(critical + blocked == wall), and the verdict agrees with the
occupancy duty table. Everything synthetic (stragglers, stability,
ledger refusals, the windowed gate) is deterministic by construction.
"""
import importlib.util
import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from pta_replicator_tpu import obs
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.obs import critpath, ledger, names, occupancy, regress
from pta_replicator_tpu.obs.serve import serve_directory, serve_url


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


def _schema_checker():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    return checker


def _span(name, t0, wall, **attrs):
    rec = {"type": "span", "name": name, "path": name, "t0": t0,
           "wall_s": wall, "cpu_s": wall, "tid": 1, "seq": 0}
    if attrs:
        rec["attrs"] = attrs
    return rec


def _chunked_schedule():
    """A hand-built two-chunk pipeline schedule with known answers:
    chunk 0 dispatch [0,1) drain [1,3) io [3,4); chunk 1 admitted at
    t=5 (1s blocked-on-window after chunk 0's dispatch ended at 1?
    no — after its own predecessors: admissions end 1, start 5 -> 4s),
    with a 0.5s queue-wait before its drain. drain is the aggregate
    bottleneck."""
    phase = _span(names.SPAN_SWEEP_PIPELINE, 0.0, 10.0)
    return [
        phase,
        _span(names.SPAN_DISPATCH, 0.0, 1.0, chunk=0),
        _span(names.SPAN_DRAIN, 1.0, 2.0, chunk=0),
        _span(names.SPAN_IO_WRITE, 3.0, 1.0, chunk=0),
        _span(names.SPAN_DISPATCH, 5.0, 1.0, chunk=1),
        _span(names.SPAN_DRAIN, 6.5, 2.5, chunk=1),
        _span(names.SPAN_IO_WRITE, 9.0, 1.0, chunk=1),
    ]


# ------------------------------------------------- real-capture DAG


def _mesh_sweep_capture(tmp_path) -> str:
    """A small but REAL pipelined mesh sweep (depth 2, 4x2 mesh over
    the conftest-forced 8 virtual CPU devices), captured."""
    from pta_replicator_tpu.parallel import make_mesh
    from pta_replicator_tpu.utils.sweep import sweep

    assert jax.device_count() >= 8, "conftest must force 8 host devices"
    d = str(tmp_path / "cap")
    b = synthetic_batch(npsr=4, ntoa=64, nbackend=2, seed=2)
    recipe = Recipe(efac=jnp.full((4, 2), 1.1))
    obs.start_capture(d, heartbeat_interval_s=0.1, stall_timeout_s=None)
    try:
        sweep(jax.random.PRNGKey(5), b, recipe, nreal=16, chunk=8,
              checkpoint_path=str(tmp_path / "ck.npz"),
              mesh=make_mesh(4, 2), pipeline_depth=2)
    finally:
        obs.finish_capture()
    return d


def test_dag_reconstruction_from_real_mesh_capture(tmp_path):
    """ISSUE 16 acceptance: the analyzer reconstructs the per-chunk
    DAG from a real depth-2 mesh capture — every chunk's chain is
    trace-coherent, the decomposition closes, and the verdict names
    the same bottleneck as the occupancy busy table (the >=95%
    attribution bound is asserted on the bench's bigger workload; a
    tiny sweep still must attribute most of the window)."""
    d = _mesh_sweep_capture(tmp_path)
    doc = critpath.analyze_capture(d)
    assert doc is not None
    assert doc["schema_version"] == critpath.CRITPATH_SCHEMA_VERSION

    stages = doc["stages"]
    assert {names.SPAN_DISPATCH, names.SPAN_DRAIN,
            names.SPAN_IO_WRITE} <= set(stages)

    # per-chunk DAG: 16 realizations / chunk 8 = 2 chains, each
    # stamped with ONE deterministic chunk trace id end to end
    chunks = doc["chunks"]
    assert chunks["count"] == 2
    assert chunks["trace_coherent_fraction"] == 1.0

    # the decomposition closes: exclusive contributions + blocked
    # time tile the window exactly
    wall = doc["window"]["wall_s"]
    critical = sum(s["critical_s"] for s in stages.values())
    assert critical == pytest.approx(doc["critical_path_s"], abs=1e-5)
    assert doc["critical_path_s"] + doc["blocked_s"] == pytest.approx(
        wall, abs=1e-5
    )
    assert 0.0 < doc["attributed_fraction"] <= 1.0

    # verdict consistency with occupancy: the top-ranked stage IS the
    # busiest stage of the duty table (greedy rank order), and its
    # exclusive critical time equals its in-window busy time
    verdict = doc["verdict"]
    busiest = max(stages, key=lambda s: stages[s]["busy_s"])
    assert verdict["bottleneck"] == busiest
    assert stages[busiest]["critical_s"] == pytest.approx(
        stages[busiest]["busy_s"], abs=1e-6
    )
    assert verdict["ranked"][0]["stage"] == busiest
    assert verdict["est_savings_s"] == stages[busiest]["critical_s"]
    assert occupancy.STAGES[busiest] in verdict["summary"]

    # offline-only: the capture itself carries no analyzer spans
    events = [json.loads(line) for line in
              open(os.path.join(d, "events.jsonl"))]
    assert not any(
        e.get("name") == names.SPAN_CRITPATH_ANALYZE for e in events
    )
    assert doc["analyzer"]["overhead_s"] >= 0.0

    # artifact: written atomically, schema-valid
    path = critpath.write_critpath(d, doc=doc)
    assert path == os.path.join(d, "critpath.json")
    assert _schema_checker().validate_critpath_file(path) == []


# ---------------------------------------------- synthetic semantics


def test_straggler_detection_on_skewed_device_schedule():
    """A skewed per-device schedule (one device 1.6x the median busy)
    is named a straggler; a balanced one is not."""
    phase = _span(names.SPAN_SWEEP_PIPELINE, 0.0, 4.0)
    skewed = [phase] + [
        _span(names.SPAN_CW_STREAM_STAGE, i * 0.1, busy, device=dev)
        for i, (dev, busy) in enumerate(
            [("d0", 1.0), ("d1", 1.0), ("d2", 1.0), ("d3", 1.6)]
        )
    ]
    doc = critpath.analyze(skewed)
    dev = doc["devices"]
    assert dev["count"] == 4
    assert dev["straggler_ratio"] == pytest.approx(1.6)
    assert dev["stragglers"] == ["d3"]

    balanced = [phase] + [
        _span(names.SPAN_CW_STREAM_STAGE, i * 0.1, 1.0, device=f"d{i}")
        for i in range(4)
    ]
    dev = critpath.analyze(balanced)["devices"]
    assert dev["straggler_ratio"] == pytest.approx(1.0)
    assert dev["stragglers"] == []


def test_chunk_chain_queue_wait_and_window_blocking():
    """The hand-built schedule's known answers: 0.5s queue-wait before
    chunk 1's drain, 4s blocked-on-window between admissions, drain
    the bottleneck of both chunks."""
    doc = critpath.analyze(_chunked_schedule())
    chunks = doc["chunks"]
    assert chunks["count"] == 2
    assert chunks["queue_wait_s"] == {names.SPAN_DRAIN: 0.5}
    assert chunks["blocked_on_window_s"] == pytest.approx(4.0)
    assert chunks["bottleneck_fraction"] == {names.SPAN_DRAIN: 1.0}
    assert doc["verdict"]["bottleneck"] == names.SPAN_DRAIN
    # drain busy 4.5s of the 10s window, all exclusive (ranked first)
    assert doc["stages"][names.SPAN_DRAIN]["critical_s"] == (
        pytest.approx(4.5)
    )


def test_verdict_stable_across_byte_identical_reruns():
    """Same events in -> byte-identical attribution out, regardless of
    record order (the analyzer must be a pure function of the capture,
    or cross-round verdict comparisons are meaningless)."""
    events = _chunked_schedule()
    a = json.dumps(critpath.analyze(events), sort_keys=True)
    b = json.dumps(critpath.analyze(events), sort_keys=True)
    c = json.dumps(
        critpath.analyze(list(reversed(events))), sort_keys=True
    )
    assert a == b == c
    assert "render" not in a  # sanity: it's the doc, not the text
    assert critpath.render_critpath(json.loads(a)) == (
        critpath.render_critpath(json.loads(b))
    )


# ------------------------------------------------------- the ledger


def _plant(root, fname, doc):
    path = os.path.join(root, fname)
    with open(path, "w") as fh:
        if isinstance(doc, str):
            fh.write(doc)
        else:
            json.dump(doc, fh)
    return path


def test_ledger_refuses_malformed_and_newer_artifacts(tmp_path):
    """Ingest never raises: a malformed artifact, a newer-schema one,
    and an empty round each degrade to a NAMED refusal with the
    reason; the good rounds still land as metric points."""
    root = str(tmp_path)
    _plant(root, "GOOD_r01.json", {"schema_version": 2, "value": 100.0})
    _plant(root, "GOOD_r02.json", {"schema_version": 2, "value": 104.0})
    _plant(root, "BROKEN_r02.json", "{not json at all")
    _plant(root, "FUTURE_r03.json", {"schema_version": 99, "value": 1.0})
    _plant(root, "EMPTY_r04.json", {"schema_version": 2})
    _plant(root, "notes.json", {"ignored": True})  # no round stamp

    led = ledger.build_ledger(root)
    assert led["schema_version"] == ledger.LEDGER_SCHEMA_VERSION
    assert led["rounds"] == 2
    assert set(led["refused"]) == {
        "BROKEN_r02.json", "FUTURE_r03.json", "EMPTY_r04.json"
    }
    assert "unreadable" in led["refused"]["BROKEN_r02.json"]
    assert "schema_version newer" in led["refused"]["FUTURE_r03.json"]
    assert "no measurements" in led["refused"]["EMPTY_r04.json"]

    m = led["metrics"]["good.value"]
    assert m["direction"] == "higher"
    assert [p["value"] for p in m["points"]] == [100.0, 104.0]
    assert [p["file"] for p in m["points"]] == [
        "GOOD_r01.json", "GOOD_r02.json"
    ]
    # every direction class the ledger emits is one regress.py knows
    assert {e["direction"] for e in led["metrics"].values()} <= set(
        ledger.DIRECTION_CLASSES
    )

    # round trip + schema validation + future-ledger refusal
    out = ledger.write_ledger(root, ledger=led)
    assert ledger.load_ledger(out) == led
    assert _schema_checker().validate_ledger_file(out) == []
    # the refusals surface in the trend view, not as tracebacks
    trend = ledger.render_trend(led, min_points=1)
    assert "BROKEN_r02.json: refused" in trend
    future = _plant(root, "LEDGER_FUTURE.json",
                    {"schema_version": 99, "metrics": {}})
    with pytest.raises(regress.SchemaMismatch):
        ledger.load_ledger(future)


def test_windowed_gate_catches_monotone_leak_pairwise_misses(tmp_path):
    """The planted 3-round leak: value decays ~4% per round — every
    PAIRWISE diff is under the 10% bench-diff threshold ('ok'), but
    the windowed gate sees the monotone trajectory and fails."""
    root = str(tmp_path)
    values = [100.0, 96.0, 92.2]
    for i, v in enumerate(values, 1):
        _plant(root, f"FAKE_r{i:02d}.json",
               {"schema_version": 2, "value": v})
    # a non-monotone neighbor must NOT trip the gate (one recovery
    # round breaks the trajectory)
    for i, v in enumerate([100.0, 96.0, 97.0], 1):
        _plant(root, f"NOISY_r{i:02d}.json",
               {"schema_version": 2, "value": v})

    # every adjacent pair is invisible to the pairwise classifier
    for old, new in zip(values, values[1:]):
        verdict, _rel = regress.classify(old, new, True, threshold=0.10)
        assert verdict == "ok"

    led = ledger.build_ledger(root)
    summary, flagged, rc = ledger.gate(led, window=3)
    assert rc == 1
    assert set(flagged) == {"fake.value"}
    assert flagged["fake.value"] == pytest.approx(0.078, abs=1e-3)
    assert "REGRESSING fake.value" in summary
    assert "pairwise diff cannot see" in summary

    # too little history -> nothing gated, gate passes
    _summary, flagged4, rc4 = ledger.gate(led, window=4)
    assert rc4 == 0 and flagged4 == {}


# ------------------------------------------- route + report round trip


def test_critpath_route_and_report_round_trip(tmp_path):
    """`/critpath` serves the written artifact byte-for-byte; the
    report renders the attribution section from it (and recomputes
    from events when the artifact is absent)."""
    d = str(tmp_path / "cap")
    os.makedirs(d)
    with open(os.path.join(d, "events.jsonl"), "w") as fh:
        for rec in _chunked_schedule():
            fh.write(json.dumps(rec) + "\n")

    from pta_replicator_tpu.obs.report import render_report

    # no artifact yet: the report recomputes the attribution inline
    out = render_report(d)
    assert "critical path (attribution over the phase window):" in out
    assert "verdict:" in out and names.SPAN_DRAIN in out

    path = critpath.write_critpath(d)
    assert path is not None
    doc = json.load(open(path))

    as_json = json.loads(render_report(d, as_json=True))
    assert as_json["critpath"]["verdict"]["bottleneck"] == (
        names.SPAN_DRAIN
    )

    server = serve_directory(d, 0, background=True)
    try:
        with urllib.request.urlopen(
            serve_url(server, "/critpath"), timeout=5
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/json"
            assert json.loads(resp.read()) == doc
        with urllib.request.urlopen(
            serve_url(server, "/"), timeout=5
        ) as resp:
            assert "/critpath" in json.loads(resp.read())["endpoints"]
    finally:
        server.shutdown()
