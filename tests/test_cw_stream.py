"""Streamed CW-catalog plane pipeline: tiled host precompute
bit-identity, double-buffered prefetch ordering/bounds/crash semantics
(mirroring test_pipeline.py's executor contract), bounded peak RSS of
the tiled build, and the on-disk tile cache's fingerprint gate."""
import json
import threading
import time
import zipfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bench import random_cw_catalog
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models import batched as B
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.parallel.pipeline import DrainTimeout
from pta_replicator_tpu.parallel.prefetch import (
    load_plane_tiles,
    load_plane_tiles_meta,
    prefetch_to_device,
    save_plane_tiles,
)


@pytest.fixture(scope="module")
def cw_setup():
    batch = synthetic_batch(npsr=5, ntoa=300, nbackend=2, seed=0)
    cat = random_cw_catalog(np.random.default_rng(3), 10_000)
    args = [jnp.asarray(r) for r in cat]
    return batch, cat, args


# -------------------------------------------------- plane bit-identity

def test_plane_tiles_bit_identical_to_monolithic(cw_setup):
    """Concatenated tiles == the monolithic plane set, exactly (the
    per-source math never crosses sources, so slicing is lossless)."""
    batch, _cat, args = cw_setup
    src_c, psr_c, _ = B.cw_catalog_planes_for(batch, *args)
    tiles = list(B.cw_catalog_plane_tiles_for(batch, *args, chunk=1024))
    assert len(tiles) == 10  # 10_000 / 1024, last tile narrower
    assert tiles[-1][0].shape[-1] == 10_000 - 9 * 1024
    np.testing.assert_array_equal(
        np.concatenate([s for s, _ in tiles], axis=-1), np.asarray(src_c)
    )
    np.testing.assert_array_equal(
        np.concatenate([p for _, p in tiles], axis=-1), np.asarray(psr_c)
    )


def test_plane_tiles_bit_identical_with_pdist_pphase(cw_setup):
    """(Np, Ns) pdist and (Ns,) pphase window along the source axis."""
    batch, cat, args = cw_setup
    rng = np.random.default_rng(9)
    ns = cat.shape[1]
    pdist = rng.uniform(0.5, 2.0, (batch.npsr, ns))
    src_c, psr_c, _ = B.cw_catalog_planes_for(batch, *args, pdist=pdist)
    tiles = B.cw_catalog_plane_tiles_for(
        batch, *args, pdist=pdist, chunk=997
    )
    np.testing.assert_array_equal(
        np.concatenate([p for _, p in tiles], axis=-1), np.asarray(psr_c)
    )
    pphase = rng.uniform(0, 2 * np.pi, ns)
    src_c, psr_c, _ = B.cw_catalog_planes_for(batch, *args, pphase=pphase)
    tiles = B.cw_catalog_plane_tiles_for(
        batch, *args, pphase=pphase, chunk=2048
    )
    np.testing.assert_array_equal(
        np.concatenate([p for _, p in tiles], axis=-1), np.asarray(psr_c)
    )


# ---------------------------------------------- response bit-identity

@pytest.mark.parametrize("depth", [1, 2, 4])
def test_streamed_response_bit_identical_at_depth(cw_setup, depth):
    """The full streamed pipeline (tiled build -> prefetch -> jitted
    accumulation) equals the monolithic scan backend EXACTLY, at every
    prefetch-window depth: same per-tile op sequence, same tile order,
    same f32 accumulation order."""
    batch, _cat, args = cw_setup
    src_c, psr_c, evolve = B.cw_catalog_planes_for(batch, *args)
    mono = np.asarray(
        B.cgw_catalog_delays_from_planes(
            batch, src_c, psr_c, evolve=evolve, chunk=1024
        )
    )
    streamed = np.asarray(
        B.cgw_catalog_delays_streamed(
            batch, *args, chunk=1024, prefetch_depth=depth
        )
    )
    np.testing.assert_array_equal(streamed, mono)


@pytest.mark.parametrize("tps", [1, 3, 8])
def test_streamed_response_bit_identical_across_groupings(cw_setup, tps):
    """Macro-tile grouping (tiles_per_step) is a dispatch-amortization
    knob only: the accumulator threads through every scan as the carry,
    so ANY grouping reproduces the monolithic accumulation order."""
    batch, _cat, args = cw_setup
    src_c, psr_c, evolve = B.cw_catalog_planes_for(batch, *args)
    mono = np.asarray(
        B.cgw_catalog_delays_from_planes(
            batch, src_c, psr_c, evolve=evolve, chunk=512
        )
    )
    streamed = np.asarray(
        B.cgw_catalog_delays_streamed(
            batch, *args, chunk=512, tiles_per_step=tps
        )
    )
    np.testing.assert_array_equal(streamed, mono)
    # the tiles_done gauge reads in TILE units at every grouping, not
    # in staged-macro units (10_000 sources / 512-wide tiles = 20)
    from pta_replicator_tpu import obs
    from pta_replicator_tpu.obs import names

    assert obs.gauge(names.CW_STREAM_TILES_DONE).value == 20


def test_stream_misaligned_tile_rejected(cw_setup):
    """A narrow tile anywhere but the stream tail would misalign the
    scan windows — must raise, not silently break bit-identity."""
    batch, _cat, _args = cw_setup

    def bad_tiles():
        from pta_replicator_tpu.ops.pallas_cw import NC_PSR, NC_SRC

        np_ = batch.npsr
        yield np.zeros((NC_SRC, 64)), np.zeros((NC_PSR, np_, 64))
        yield np.zeros((NC_SRC, 32)), np.zeros((NC_PSR, np_, 32))
        yield np.zeros((NC_SRC, 64)), np.zeros((NC_PSR, np_, 64))

    with pytest.raises(ValueError, match="width"):
        B.cw_stream_response(batch, bad_tiles(), evolve=True)


def test_streamed_response_linear_modes_bit_identical(cw_setup):
    """The non-evolve kernel variants (phase-approx, monochromatic)
    stream identically too — the evolve flag travels with the planes."""
    batch, _cat, args = cw_setup
    for kw in (
        dict(evolve=False, phase_approx=True),
        dict(evolve=False, phase_approx=False),
    ):
        mono = np.asarray(
            B.cgw_catalog_delays(batch, *args, chunk=512, **kw)
        )
        streamed = np.asarray(
            B.cgw_catalog_delays_streamed(batch, *args, chunk=512, **kw)
        )
        np.testing.assert_array_equal(streamed, mono)


def test_recipe_streamed_routing_bit_identical(cw_setup):
    """Recipe.cgw_stream_chunk routes deterministic_delays through the
    streamed pipeline with identical results (so sweeps/benches can
    flip one static field to go bounded-memory)."""
    import dataclasses

    batch, cat, _args = cw_setup
    r_mono = Recipe(cgw_params=jnp.asarray(cat), cgw_chunk=1024)
    r_stream = dataclasses.replace(r_mono, cgw_stream_chunk=1024)
    np.testing.assert_array_equal(
        np.asarray(B.deterministic_delays(batch, r_stream)),
        np.asarray(B.deterministic_delays(batch, r_mono)),
    )


def test_streamed_requires_concrete_params(cw_setup):
    """Tracer params must raise with guidance, not silently demote the
    f64 host precompute (the monolithic traced fallback has no streamed
    analog — streaming exists for the bounded-memory HOST build)."""
    batch, _cat, args = cw_setup

    def traced(theta):
        return B.cgw_catalog_delays_streamed(batch, theta, *args[1:])

    with pytest.raises(TypeError, match="concrete"):
        jax.jit(traced)(args[0])


@pytest.mark.slow
def test_streamed_response_bit_identical_1e5(cw_setup):
    batch, _cat, _args = cw_setup
    cat = random_cw_catalog(np.random.default_rng(11), 100_000)
    args = [jnp.asarray(r) for r in cat]
    mono = np.asarray(B.cgw_catalog_delays(batch, *args, chunk=4096))
    streamed = np.asarray(
        B.cgw_catalog_delays_streamed(batch, *args, chunk=4096)
    )
    np.testing.assert_array_equal(streamed, mono)


# --------------------------------------------------- prefetch executor

def test_prefetch_orders_and_bounds():
    """Tiles come out strictly in input order; never more than ``depth``
    tiles exist past the host generator at once."""
    outstanding = [0]  # built but not yet consumed
    peak = [0]
    lock = threading.Lock()

    def tiles():
        for i in range(12):
            with lock:
                outstanding[0] += 1
                peak[0] = max(peak[0], outstanding[0])
            yield np.full((4,), i)

    got = []
    for staged in prefetch_to_device(tiles(), depth=3):
        time.sleep(0.005)  # let the worker run ahead into the window
        got.append(int(np.asarray(staged)[0]))
        with lock:
            outstanding[0] -= 1
    assert got == list(range(12))
    assert peak[0] <= 3 + 1  # window + the one being consumed


def test_prefetch_depth1_is_serial():
    """depth=1: tile k+1 is not built until tile k was consumed."""
    events = []

    def tiles():
        for i in range(4):
            events.append(("build", i))
            yield np.asarray([i])

    for i, staged in enumerate(prefetch_to_device(tiles(), depth=1)):
        time.sleep(0.02)
        events.append(("consume", i))
    builds_before_first_consume = [
        e for e in events[: events.index(("consume", 0))] if e[0] == "build"
    ]
    assert builds_before_first_consume == [("build", 0)]


def test_prefetch_depth0_rejected():
    with pytest.raises(ValueError, match="depth"):
        list(prefetch_to_device(iter([np.zeros(1)]), depth=0))


def test_prefetch_propagates_tile_build_exception_unchanged():
    """A tile-build crash re-raises UNCHANGED on the consumer, after
    every earlier tile was delivered in order (mirror of the pipelined
    executor's stage-exception contract)."""

    class Boom(Exception):
        pass

    def tiles():
        yield np.asarray([0])
        yield np.asarray([1])
        raise Boom("tile build failed")

    got = []
    with pytest.raises(Boom):
        for staged in prefetch_to_device(tiles(), depth=2):
            got.append(int(np.asarray(staged)[0]))
    assert got == [0, 1]


def test_prefetch_propagates_place_exception_unchanged():
    class Boom(Exception):
        pass

    def place(tile):
        if int(tile[0]) == 2:
            raise Boom("staging failed")
        return tile

    got = []
    with pytest.raises(Boom):
        for staged in prefetch_to_device(
            (np.asarray([i]) for i in range(5)), depth=2, place=place
        ):
            got.append(int(staged[0]))
    assert got == [0, 1]


def test_prefetch_stall_timeout():
    """A wedged device_put (hung tunnel) raises DrainTimeout fast — the
    same failure type a wedged sweep readback raises."""
    hang = threading.Event()

    def place(tile):
        hang.wait(20.0)  # never set: simulated wedge
        return tile

    t0 = time.monotonic()
    with pytest.raises(DrainTimeout):
        for _ in prefetch_to_device(
            (np.asarray([i]) for i in range(3)),
            depth=2, place=place, stall_timeout_s=0.4,
        ):
            pass
    assert time.monotonic() - t0 < 10.0
    hang.set()


def test_prefetch_consumer_abandon_stops_worker():
    """Breaking out of the consumer loop (exception upstream) must stop
    the worker thread promptly, not leak it spinning on the window."""
    built = [0]

    def tiles():
        for i in range(100):
            built[0] += 1
            yield np.asarray([i])

    gen = prefetch_to_device(tiles(), depth=2)
    next(gen)
    gen.close()  # consumer abandons
    time.sleep(0.3)
    assert built[0] <= 5  # worker stopped near the window bound


# ------------------------------------------------ bounded-memory build

from pta_replicator_tpu.utils.profiling import vm_rss_mb as _vm_rss_mb


def test_tiled_build_peak_rss_bounded():
    """Iterating the tiled precompute at a shape whose MONOLITHIC f64
    plane set needs >=300 MB (6 x 32 x 2e5 x 8B for the psr stack
    alone, ~3x that with intermediates) must not grow RSS by more than
    ~a tile's worth of working set."""
    batch = synthetic_batch(npsr=32, ntoa=64, nbackend=2, seed=1)
    cat = random_cw_catalog(np.random.default_rng(5), 200_000)
    rss0 = _vm_rss_mb()
    if rss0 == 0.0:
        pytest.skip("no /proc VmRSS on this platform")
    peak = rss0
    ntiles = 0
    for src_t, psr_t in B.cw_catalog_plane_tiles_for(
        batch, *cat, chunk=4096
    ):
        assert src_t.shape[-1] <= 4096
        ntiles += 1
        if ntiles % 8 == 0:
            peak = max(peak, _vm_rss_mb())
    peak = max(peak, _vm_rss_mb())
    assert ntiles == 49
    growth = peak - rss0
    assert growth < 200.0, (
        f"tiled plane build grew RSS by {growth:.0f} MB — the bounded-"
        "memory contract (O(Np x chunk), not O(Np x Ns)) is broken"
    )


# ------------------------------------------------------- tile cache

def test_tile_cache_roundtrip_identity(tmp_path, cw_setup):
    """save -> load -> stream equals the monolithic response exactly;
    metadata and tile count survive the roundtrip."""
    batch, _cat, args = cw_setup
    path = str(tmp_path / "tiles.npz")
    n = save_plane_tiles(
        path,
        B.cw_catalog_plane_tiles_for(batch, *args, chunk=1024),
        fingerprint="fp-abc",
        meta={"evolve": True, "chunk": 1024},
    )
    assert n == 10
    meta, tiles = load_plane_tiles(path, expect_fingerprint="fp-abc")
    assert meta["ntiles"] == 10 and meta["chunk"] == 1024
    src_c, psr_c, evolve = B.cw_catalog_planes_for(batch, *args)
    mono = np.asarray(
        B.cgw_catalog_delays_from_planes(
            batch, src_c, psr_c, evolve=evolve, chunk=1024
        )
    )
    streamed = np.asarray(
        B.cw_stream_response(batch, tiles, evolve=True, prefetch_depth=2)
    )
    np.testing.assert_array_equal(streamed, mono)


def test_tile_cache_fingerprint_refusal(tmp_path, cw_setup):
    batch, _cat, args = cw_setup
    path = str(tmp_path / "tiles.npz")
    save_plane_tiles(
        path,
        B.cw_catalog_plane_tiles_for(batch, *args, chunk=4096),
        fingerprint="fp-old",
    )
    with pytest.raises(ValueError, match="fingerprint"):
        load_plane_tiles(path, expect_fingerprint="fp-new")
    # without an expectation the cache still opens (inspection tools)
    meta, _ = load_plane_tiles(path)
    assert meta["fingerprint"] == "fp-old"


def test_tile_cache_truncated_archive_refused(tmp_path):
    """Tiles are written before the meta member, so an archive that
    died mid-write has no meta and must be refused, not half-read."""
    path = str(tmp_path / "trunc.npz")
    with zipfile.ZipFile(path, "w") as zf:
        with zf.open("src000000.npy", "w") as fh:
            bio = np.lib.format
            import io

            b = io.BytesIO()
            np.save(b, np.zeros((9, 4)), allow_pickle=False)
            fh.write(b.getbuffer())
    with pytest.raises(ValueError, match="meta"):
        load_plane_tiles_meta(path)
