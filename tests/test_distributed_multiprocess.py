"""Real multi-process distributed execution rehearsal.

The reference has no distributed backend at all (SURVEY.md section 2);
this framework's multi-host story (parallel/distributed.py) is the
standard JAX SPMD recipe. Everything below exercises it with two real
OS processes joined over localhost GRPC — the same code path a Cloud TPU
pod uses across hosts — and checks that the per-process
``local_realizations`` blocks stitch into exactly the single-process
result.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import jax
import pytest

from pta_replicator_tpu.models import batched as B


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: jaxlib's CPU collective-runtime gap: multi-process SPMD over
#: localhost GRPC joins fine, but executing a cross-process computation
#: raises this from the CPU client. The code path under test is real
#: (it IS the Cloud TPU pod path); only the CPU rehearsal backend can't
#: run it, so the absence is reported as an explicit skip naming the
#: jaxlib limitation — not as a test failure.
_JAXLIB_MULTIPROCESS_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "multiprocess computations aren't implemented",
)


def _skip_on_jaxlib_multiprocess_limit(workers, logs) -> None:
    """Convert a worker failure caused by jaxlib's missing CPU
    multiprocess runtime into a pytest.skip; any other failure still
    fails loudly with the worker log."""
    if all(w.returncode == 0 for w in workers):
        return
    for log in logs:
        low = (log or "").lower()
        if any(m.lower() in low for m in _JAXLIB_MULTIPROCESS_MARKERS):
            pytest.skip(
                "jaxlib limitation: \"Multiprocess computations aren't "
                "implemented on the CPU backend\" — the distributed "
                "SPMD path needs a real multi-host backend (TPU pod); "
                "the localhost-GRPC rehearsal stops at execution"
            )


@pytest.mark.parametrize("n_psr", [1, 2])
def test_two_process_shardmap_matches_single_process(n_psr, tmp_path):
    """2 processes x 4 virtual CPU devices run shardmap_realize over the
    joint 8-device mesh — realization-only (8,1) and pulsar-sharded (4,2)
    — and each host's local block must equal its slice of the
    single-process realization array (local_realizations stitches the
    psr axis back together)."""
    port = _free_port()
    outs = [tmp_path / f"w{i}.npz" for i in range(2)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    workers = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(os.path.dirname(__file__), "_dist_worker.py"),
                str(port),
                str(i),
                str(outs[i]),
                str(n_psr),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    logs = []
    for w in workers:
        try:
            out, _ = w.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for ww in workers:
                ww.kill()
            pytest.fail("distributed worker timed out (GRPC join hung?)")
        logs.append(out)
    _skip_on_jaxlib_multiprocess_limit(workers, logs)
    for i, w in enumerate(workers):
        assert w.returncode == 0, f"worker {i} failed:\n{logs[i][-2000:]}"

    # single-process reference: same key, same workload (shared builder)
    import _dist_worker as DW

    batch, recipe = DW.build_workload()
    ref = np.asarray(
        B.realize(jax.random.PRNGKey(9), batch, recipe, nreal=16, fit=True)
    )

    seen = np.zeros(16, dtype=bool)
    for path in outs:
        data = np.load(path)
        local = data["local"]
        pid = int(data["process_index"])
        assert int(data["global_device_count"]) == 8
        # process 0 owns devices 0-3: the first half of the 'real' axis
        # whether the mesh is (8,1) or (4,2); local_realizations stitches
        # the psr columns, so each local block spans the full pulsar axis
        lo = pid * 8
        np.testing.assert_allclose(
            local,
            ref[lo : lo + 8],
            rtol=1e-9,
            atol=1e-9 * float(np.sqrt(np.mean(ref**2))),
        )
        seen[lo : lo + 8] = True
    assert seen.all(), "the two hosts' blocks must tile all realizations"


def test_four_process_psr_sharded_matches_single_process(tmp_path):
    """4 processes x 2 virtual CPU devices over the joint 8-device
    ('real'=4, 'psr'=2) mesh (VERDICT r3 item 6): pulsar sharding spans
    processes while realizations span the process grid, and every
    host's local block must equal its realization slice of the
    single-process result."""
    port = _free_port()
    nproc = 4
    outs = [tmp_path / f"w{i}.npz" for i in range(nproc)]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    workers = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(os.path.dirname(__file__), "_dist_worker.py"),
                str(port),
                str(i),
                str(outs[i]),
                "2",        # n_psr: pulsar axis sharded 2-way
                str(nproc),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    logs = []
    for w in workers:
        try:
            out, _ = w.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for ww in workers:
                ww.kill()
            pytest.fail("distributed worker timed out (GRPC join hung?)")
        logs.append(out)
    _skip_on_jaxlib_multiprocess_limit(workers, logs)
    for i, w in enumerate(workers):
        assert w.returncode == 0, f"worker {i} failed:\n{logs[i][-2000:]}"

    import _dist_worker as DW

    batch, recipe = DW.build_workload()
    ref = np.asarray(
        B.realize(jax.random.PRNGKey(9), batch, recipe, nreal=16, fit=True)
    )

    seen = np.zeros(16, dtype=bool)
    for path in outs:
        data = np.load(path)
        local = data["local"]
        pid = int(data["process_index"])
        assert int(data["global_device_count"]) == 8
        assert int(data["local_device_count"]) == 2
        # device grid is row-major (real, psr): process p owns devices
        # 2p..2p+1 = one 'real' row x both 'psr' columns -> realization
        # block [4p : 4p+4] spanning the full stitched pulsar axis
        lo = pid * 4
        np.testing.assert_allclose(
            local,
            ref[lo : lo + 4],
            rtol=1e-9,
            atol=1e-9 * float(np.sqrt(np.mean(ref**2))),
        )
        seen[lo : lo + 4] = True
    assert seen.all(), "the four hosts' blocks must tile all realizations"
