"""Float32 is the production dtype on TPU (f64 is emulated and slow), so
every device op needs f32 accuracy evidence against its f64 form — the
round-1 gap flagged in VERDICT.md ("production dtype is never tested";
the evolve-mode CW catalog had a ~2% systematic f32 error from
absolute-time chirp cancellation, fixed by the epoch-folded planes in
ops.pallas_cw).

Deterministic ops are compared f32-vs-f64 directly; stochastic ops are
validated statistically at f32 (their f32/f64 draws are different bit
streams by construction).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models import batched as B


def _rel_rms(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return float(np.sqrt(np.mean((a - b) ** 2) / np.mean(b**2)))


@pytest.fixture(scope="module")
def batches():
    b64 = synthetic_batch(npsr=8, ntoa=1024, nbackend=3, seed=3,
                          dtype=jnp.float64)
    return b64, b64.astype(jnp.float32)


@pytest.fixture(scope="module")
def catalog():
    n = 60
    rng = np.random.default_rng(11)
    return dict(
        gwtheta=np.arccos(rng.uniform(-1, 1, n)),
        gwphi=rng.uniform(0, 2 * np.pi, n),
        mc=10 ** rng.uniform(8, 9.5, n),
        dist=rng.uniform(20, 500, n),
        fgw=10 ** rng.uniform(-8.8, -7.5, n),
        phase0=rng.uniform(0, 2 * np.pi, n),
        psi=rng.uniform(0, np.pi, n),
        inc=np.arccos(rng.uniform(-1, 1, n)),
    )


@pytest.mark.parametrize(
    "mode",
    [
        dict(evolve=True, phase_approx=False),
        dict(evolve=False, phase_approx=True),
        dict(evolve=False, phase_approx=False),
    ],
    ids=["evolve", "phase_approx", "mono"],
)
@pytest.mark.parametrize("backend", ["scan", "pallas_interpret"])
def test_cw_catalog_f32_accuracy(batches, catalog, mode, backend):
    """The VERDICT.md round-2 'done' criterion: f32 CW catalog matches
    f64 to <1e-3 relative rms in every evolution mode (the folded planes
    give ~1e-5; round 1 was ~2% in evolve mode)."""
    b64, b32 = batches
    tref = 53000 * 86400.0
    kw = dict(tref_s=tref, pdist=1.2, backend=backend, **mode)
    d64 = B.cgw_catalog_delays(b64, *catalog.values(), **kw)
    d32 = B.cgw_catalog_delays(b32, *catalog.values(), **kw)
    assert d32.dtype == jnp.float32
    assert _rel_rms(d32, d64) < 1e-3


def test_cw_catalog_f32_pphase_pdist_vectors(batches, catalog):
    """Per-source pdist and explicit pphase stay f32-accurate too."""
    b64, b32 = batches
    n = len(catalog["mc"])
    rng = np.random.default_rng(12)
    pdist = rng.uniform(0.3, 3.0, n)
    pphase = rng.uniform(0, 2 * np.pi, n)
    for kw in (dict(pdist=pdist), dict(pphase=pphase)):
        d64 = B.cgw_catalog_delays(b64, *catalog.values(), **kw)
        d32 = B.cgw_catalog_delays(b32, *catalog.values(), **kw)
        assert _rel_rms(d32, d64) < 1e-3


def test_gw_memory_f32(batches):
    b64, b32 = batches
    args = dict(strain=5e-15, gwtheta=1.1, gwphi=2.3, bwm_pol=0.7,
                t0_mjd=55500.0)
    d64 = B.gw_memory_delays(b64, **args)
    d32 = B.gw_memory_delays(b32, **args)
    assert _rel_rms(d32, d64) < 1e-5


def test_burst_f32(batches):
    b64, b32 = batches
    g = np.linspace(0, 1, 256)
    hp, hc = 1e-13 * np.sin(9 * g) * g, 1e-13 * np.cos(7 * g) * g
    span = float(b64.tspan_s[0])
    args = dict(gwtheta=0.9, gwphi=1.0, hplus_grid=hp, hcross_grid=hc,
                grid_start_s=-span / 4, grid_stop_s=span / 4, psi=0.4)
    d64 = B.burst_delays(b64, **args)
    d32 = B.burst_delays(b32, **args)
    assert _rel_rms(d32, d64) < 1e-4


def test_transient_f32(batches):
    b64, b32 = batches
    wf = 1e-7 * np.hanning(128)
    span = float(b64.tspan_s[0])
    args = dict(psr_index=2, waveform_grid=wf, grid_start_s=-span / 8,
                grid_stop_s=span / 8)
    d64 = B.transient_delays(b64, **args)
    d32 = B.transient_delays(b32, **args)
    assert _rel_rms(d32, d64 + 1e-300) < 1e-4


def test_quadratic_fit_f32(batches):
    """The refit projection (normalized time basis) stays well
    conditioned in f32."""
    b64, b32 = batches
    key = jax.random.PRNGKey(7)
    d64 = B.red_noise_delays(key, b64, -13.5, 4.0)
    d32 = d64.astype(jnp.float32)
    f64 = B.quadratic_fit_subtract(d64, b64)
    f32 = B.quadratic_fit_subtract(d32, b32)
    assert _rel_rms(f32, f64) < 1e-3


def test_white_noise_f32_statistics(batches):
    """Stochastic op at f32: per-TOA variance matches the analytic
    EFAC/EQUAD expectation."""
    _, b32 = batches
    keys = jax.random.split(jax.random.PRNGKey(1), 2000)
    d = jax.vmap(
        lambda k: B.white_noise_delays(k, b32, efac=1.4, log10_equad=-6.2)
    )(keys)
    assert d.dtype == jnp.float32
    var = np.var(np.asarray(d), axis=0)
    expect = 1.4**2 * np.asarray(b32.errors_s) ** 2 + 1.4**2 * (10**-6.2) ** 2
    np.testing.assert_allclose(var, expect, rtol=0.2)


def test_red_noise_f32_statistics(batches):
    """Red-noise delay variance at f32 matches the f64 op's variance to a
    few percent over realizations (same physics, different draws)."""
    b64, b32 = batches
    keys = jax.random.split(jax.random.PRNGKey(2), 600)
    v32 = np.var(
        np.asarray(
            jax.vmap(lambda k: B.red_noise_delays(k, b32, -13.8, 3.8))(keys)
        )
    )
    v64 = np.var(
        np.asarray(
            jax.vmap(lambda k: B.red_noise_delays(k, b64, -13.8, 3.8))(keys)
        )
    )
    assert abs(v32 / v64 - 1.0) < 0.15


def test_gwb_f32_statistics(batches):
    """GWB realization rms at f32 agrees with f64 statistically, and the
    cross-pulsar mix stays finite/masked."""
    b64, b32 = batches
    orf = np.sqrt(2.0) * np.eye(8)
    keys = jax.random.split(jax.random.PRNGKey(3), 64)

    def rms(b, dtype):
        d = jax.vmap(
            lambda k: B.gwb_delays(
                k, b, -14.0, 4.33, jnp.asarray(orf, dtype), npts=300,
                howml=4,
            )
        )(keys)
        assert bool(jnp.all(jnp.isfinite(d)))
        return float(jnp.sqrt(jnp.mean(d**2)))

    r32 = rms(b32, jnp.float32)
    r64 = rms(b64, jnp.float64)
    assert abs(r32 / r64 - 1.0) < 0.1


def test_full_recipe_f32_realize(batches):
    """End-to-end realize() in f32: finite, right dtype, rms within a few
    percent of the f64 run (statistical)."""
    b64, b32 = batches
    rng = np.random.default_rng(4)
    ncw = 20
    cat = np.stack(
        [
            np.arccos(rng.uniform(-1, 1, ncw)),
            rng.uniform(0, 2 * np.pi, ncw),
            10 ** rng.uniform(8, 9.3, ncw),
            rng.uniform(50, 800, ncw),
            10 ** rng.uniform(-8.8, -7.8, ncw),
            rng.uniform(0, 2 * np.pi, ncw),
            rng.uniform(0, np.pi, ncw),
            np.arccos(rng.uniform(-1, 1, ncw)),
        ]
    )
    orf = np.sqrt(2.0) * np.eye(8)

    def run(b, dtype):
        recipe = B.Recipe(
            efac=jnp.asarray(1.1, dtype),
            log10_equad=jnp.asarray(-6.5, dtype),
            log10_ecorr=jnp.asarray(-6.8, dtype),
            rn_log10_amplitude=jnp.asarray(-14.0, dtype),
            rn_gamma=jnp.asarray(4.0, dtype),
            gwb_log10_amplitude=jnp.asarray(-14.2, dtype),
            gwb_gamma=jnp.asarray(4.33, dtype),
            orf_cholesky=jnp.asarray(orf, dtype),
            cgw_params=jnp.asarray(cat, dtype),
            gwb_npts=300,
            gwb_howml=4.0,
        )
        return B.realize(jax.random.PRNGKey(9), b, recipe, nreal=32,
                         fit=True)

    r32 = run(b32, jnp.float32)
    r64 = run(b64, jnp.float64)
    assert r32.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(r32)))
    rms32 = float(jnp.sqrt(jnp.mean(r32**2)))
    rms64 = float(jnp.sqrt(jnp.mean(r64**2)))
    assert abs(rms32 / rms64 - 1.0) < 0.1


def test_powerlaw_prior_no_f32_underflow():
    """The power-law prior must not flush to zero at high mode numbers in
    f32: the naive evaluation order's intermediate (amp^2 (f yr)^-gamma
    / (12 pi^2 T)) sits in the subnormal range for typical PTA
    amplitudes, truncating the injected red-noise spectrum at ~12 of 30
    modes on device (caught by benchmarks/validate_device.py). The
    log-space form keeps every mode finite and positive down to
    amplitudes far below physical."""
    import numpy as np

    from pta_replicator_tpu.ops.fourier import fourier_frequencies, powerlaw_prior

    T = np.float32(16 * 365.25 * 86400.0)
    freqs = np.asarray(
        fourier_frequencies(T, nmodes=30), np.float32
    )
    for log10_A in (-13.8, -16.0, -18.0):
        prior = powerlaw_prior(
            np.repeat(freqs, 2, axis=-1).astype(np.float32),
            np.float32(log10_A), np.float32(4.33), T, xp=np,
        )
        assert prior.dtype == np.float32
        assert np.all(prior > 0), (log10_A, prior)
        # and the values match the f64 evaluation to f32 roundoff
        prior64 = powerlaw_prior(
            np.repeat(freqs, 2, axis=-1).astype(np.float64),
            log10_A, 4.33, float(T), xp=np,
        )
        np.testing.assert_allclose(prior, prior64, rtol=2e-5)


def test_f32_pipeline_variance_budget():
    """The f32 device pipeline's variance budget matches the analytic sum
    — the dtype-sensitive sibling of test_pipeline_variance_matches_
    analytic (which runs x64). A broad guard against f32 scale/underflow
    defects in any op's draw chain; note that subnormal flushing is
    backend/compilation dependent (the round-3 prior flush reproduced
    under compiled pipelines, not reliably in eager CPU ops), so the
    *deterministic* guard for that bug is
    test_powerlaw_prior_no_f32_underflow."""
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.ops.fourier import fourier_frequencies, powerlaw_prior

    npsr, ntoa, nreal = 4, 1024, 512
    b = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=2, seed=5,
                        dtype=jnp.float32)
    f32 = jnp.float32
    recipe = B.Recipe(
        efac=jnp.full((npsr, 2), 1.2, f32),
        log10_equad=jnp.full((npsr, 2), -6.3, f32),
        log10_ecorr=jnp.full((npsr, 2), -6.4, f32),
        # gamma ~ 0.8: a flat spectrum spreads power across all modes, so
        # a high-mode flush moves the total budget far more than a steep
        # gamma would
        rn_log10_amplitude=jnp.full(npsr, -13.9, f32),
        rn_gamma=jnp.full(npsr, 0.8, f32),
    )
    res = np.asarray(B.realize(jax.random.PRNGKey(3), b, recipe, nreal=nreal))
    assert res.dtype == np.float32
    meas = res.var(axis=0).mean(axis=-1)

    efac, equad, ecorr = 1.2, 10.0**-6.3, 10.0**-6.4
    white = (efac * np.asarray(b.errors_s, np.float64)) ** 2 + (efac * equad) ** 2
    freqs = np.asarray(fourier_frequencies(np.asarray(b.tspan_s, np.float64),
                                           nmodes=30))
    prior = np.asarray(
        powerlaw_prior(
            np.repeat(freqs, 2, axis=-1),
            np.full(npsr, -13.9), np.full(npsr, 0.8),
            np.asarray(b.tspan_s, np.float64),
        )
    )
    want = white.mean(axis=-1) + ecorr**2 + prior.sum(axis=-1) / 2.0
    np.testing.assert_allclose(meas, want, rtol=0.12)


def test_gls_fit_f32(batches):
    """The nested-Woodbury GLS projection (column-normalized normal
    equations, per-epoch segment Woodbury, (R,R) solve) stays well
    conditioned at the production dtype."""
    b64, b32 = batches
    rng = np.random.default_rng(9)
    nb = int(np.asarray(b64.backend_index).max()) + 1
    recipe64 = B.Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.3, (b64.npsr, nb))),
        log10_ecorr=jnp.asarray(rng.uniform(-6.8, -6.4, (b64.npsr, nb))),
        rn_log10_amplitude=jnp.full(b64.npsr, -13.6),
        rn_gamma=jnp.full(b64.npsr, 3.8),
    )
    t = np.asarray(b64.toas_s)
    D = np.stack([
        np.ones_like(t),
        t / np.asarray(b64.tspan_s)[:, None],
        (t / np.asarray(b64.tspan_s)[:, None]) ** 2,
    ], axis=-1)
    key = jax.random.PRNGKey(5)
    d64 = B.red_noise_delays(key, b64, -13.5, 4.0)
    f64 = B.gls_fit_subtract(d64, b64, jnp.asarray(D), recipe64)
    f32 = B.gls_fit_subtract(
        d64.astype(jnp.float32), b32,
        jnp.asarray(D, jnp.float32),
        jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            recipe64,
        ),
    )
    assert _rel_rms(f32, f64) < 1e-3
