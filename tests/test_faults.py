"""Fault injection + supervised recovery (pta_replicator_tpu/faults/,
docs/robustness.md): schedule grammar, trigger determinism, the shared
transient-vs-fatal classifier and backoff policy, the sweep's
chunk-retry supervision (byte-identity through injected transient
failures, stalls, and torn checkpoint writes — the chaos gate's fast
subset), and the prefetch staging retry.

Fixture-free and CPU-only: part of scripts/check.sh's pre-push gate.
"""
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.faults import inject, retry
from pta_replicator_tpu.faults.inject import InjectedFault
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.obs import counter, names
from pta_replicator_tpu.parallel.pipeline import DrainTimeout
from pta_replicator_tpu.parallel.prefetch import prefetch_to_device
from pta_replicator_tpu.utils.sweep import sweep

#: fast in-process recovery for tests (production default backs off
#: 0.5 s+ per retry — pure wasted wall under injected faults)
FAST = retry.RetryPolicy(max_attempts=4, base_delay_s=0.01,
                         multiplier=2.0, max_delay_s=0.1, jitter=0.0)


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends disarmed — a leaked schedule would
    chaos unrelated tests."""
    inject.disarm()
    yield
    inject.disarm()


@pytest.fixture()
def small_sweep():
    b = synthetic_batch(npsr=3, ntoa=64, seed=2)
    recipe = Recipe(
        efac=jnp.ones(3),
        rn_log10_amplitude=jnp.full(3, -14.0),
        rn_gamma=jnp.full(3, 4.0),
    )
    return b, recipe, jax.random.PRNGKey(5)


# ------------------------------------------------------- schedule grammar

def test_parse_schedule_roundtrip():
    text = ("drain:raise@chunk=2;checkpoint_write:torn@call=3;"
            "dispatch:stall=2.5@chunk=1x2;cw_stream_stage:device_lost@p=0.1")
    specs = inject.parse_schedule(text)
    assert [s.spec_str() for s in specs] == [
        "drain:raise@chunk=2", "checkpoint_write:torn@call=3",
        "dispatch:stall=2.5@chunk=1x2", "cw_stream_stage:device_lost@p=0.1",
    ]
    assert specs[2].stall_s == 2.5 and specs[2].max_fires == 2


@pytest.mark.parametrize("bad", [
    "nosite:raise@chunk=1",        # unknown site
    "drain:explode@chunk=1",       # unknown kind
    "drain:raise@tick=1",          # unknown trigger
    "drain:raise",                 # no trigger
    "drain:torn@call=1",           # torn outside the checkpoint sites
    "drain:raise@p=1.5",           # p out of range
    "drain:raise@call=0",          # call is 1-based
    "drain:raise=3@chunk=1",       # parameter on a parameterless kind
])
def test_parse_schedule_refuses_malformed(bad):
    with pytest.raises(ValueError, match="bad fault spec"):
        inject.parse_schedule(bad)


# ------------------------------------------------------------- triggers

def test_fire_disarmed_is_noop_and_cheap():
    assert not inject.is_armed()
    inject.fire("drain", chunk=3)  # must not raise, log, or import obs


def test_chunk_trigger_fires_once():
    with inject.armed("drain:raise@chunk=2"):
        inject.fire("drain", chunk=0)
        inject.fire("drain", chunk=1)
        with pytest.raises(InjectedFault, match="drain.*raise"):
            inject.fire("drain", chunk=2)
        inject.fire("drain", chunk=2)  # max_fires=1: exhausted
        assert len(inject.fired()) == 1


def test_call_trigger_counts_per_site():
    with inject.armed("io_write:raise@call=3"):
        inject.fire("io_write", chunk=0)
        inject.fire("drain", chunk=0)  # other site: not counted
        inject.fire("io_write", chunk=1)
        with pytest.raises(InjectedFault):
            inject.fire("io_write", chunk=2)


def test_two_call_triggers_at_one_site_both_fire_on_time():
    """A firing must not shift later same-site specs' call counters:
    call=2 and call=3 at one site fire at exactly calls 2 and 3."""
    hits = []
    with inject.armed("drain:raise@call=2;drain:raise@call=3"):
        for k in range(5):
            try:
                inject.fire("drain", chunk=k)
            except InjectedFault:
                hits.append(k)
    assert hits == [1, 2]  # 2nd and 3rd calls (0-indexed loop)


def test_probabilistic_trigger_is_seeded_deterministic():
    def run(seed):
        hits = []
        with inject.armed("drain:raise@p=0.3x100", seed=seed):
            for k in range(50):
                try:
                    inject.fire("drain", chunk=k)
                except InjectedFault:
                    hits.append(k)
        return hits

    a, b = run(7), run(7)
    assert a == b and len(a) > 0
    assert run(8) != a  # a different seed is a different schedule


def test_tile_index_matches_chunk_trigger():
    with inject.armed("cw_stream_stage:raise@chunk=1"):
        inject.fire("cw_stream_stage", tile=0)
        with pytest.raises(InjectedFault):
            inject.fire("cw_stream_stage", tile=1)


def test_arm_from_env(monkeypatch):
    monkeypatch.setenv("PTA_FAULTS", "drain:raise@chunk=0")
    monkeypatch.setenv("PTA_FAULTS_SEED", "3")
    assert inject.arm_from_env()
    with pytest.raises(InjectedFault):
        inject.fire("drain", chunk=0)
    monkeypatch.delenv("PTA_FAULTS")
    inject.disarm()
    assert not inject.arm_from_env()


# ---------------------------------------------------------- fault kinds

def test_kind_fatal_is_not_transient():
    with inject.armed("drain:fatal@chunk=0"):
        with pytest.raises(InjectedFault) as ei:
            inject.fire("drain", chunk=0)
    assert ei.value.transient is False
    assert not retry.is_transient(ei.value)


def test_kind_enospc_raises_oserror():
    import errno

    with inject.armed("checkpoint_write:enospc@call=1"):
        with pytest.raises(OSError) as ei:
            inject.fire("checkpoint_write", path="/tmp/x")
    assert ei.value.errno == errno.ENOSPC
    assert retry.is_transient(ei.value)


def test_kind_stall_sleeps_without_raising():
    with inject.armed("drain:stall=0.05@chunk=0"):
        t0 = time.monotonic()
        inject.fire("drain", chunk=0)
        assert time.monotonic() - t0 >= 0.05


def test_kind_torn_truncates_the_inflight_file(tmp_path):
    p = tmp_path / "victim.bin"
    p.write_bytes(b"x" * 1000)
    with inject.armed("checkpoint_write:torn@call=1"):
        with pytest.raises(InjectedFault, match="torn"):
            inject.fire("checkpoint_write", path=str(p))
    assert p.stat().st_size == 500  # genuinely torn, not just raised


# ------------------------------------------------- classifier + backoff

@pytest.mark.parametrize("exc,transient", [
    (InjectedFault("drain", "raise"), True),
    (InjectedFault("drain", "fatal", transient=False), False),
    (DrainTimeout("host readback exceeded 900s"), True),
    (ConnectionResetError(), True),
    (OSError(28, "No space left on device"), True),       # ENOSPC
    (OSError(2, "No such file or directory"), False),     # ENOENT
    (RuntimeError("DEVICE_LOST: device is gone"), True),
    (RuntimeError("UNAVAILABLE: socket closed"), True),
    (RuntimeError("something unrelated"), False),
    (ValueError("checkpoint belongs to a different sweep"), False),
    (KeyboardInterrupt(), False),
])
def test_is_transient_classification(exc, transient):
    assert retry.is_transient(exc) is transient


def test_backoff_ladder_shape_and_determinism():
    # bench.py's proven tunnel ladder: 20 s then 40 s, +/-25% jitter
    d1 = retry.backoff_delay(1, retry.TUNNEL_POLICY, seed=0)
    d2 = retry.backoff_delay(2, retry.TUNNEL_POLICY, seed=0)
    assert 15.0 <= d1 <= 25.0 and 30.0 <= d2 <= 50.0
    assert d1 == retry.backoff_delay(1, retry.TUNNEL_POLICY, seed=0)
    nojit = retry.RetryPolicy(base_delay_s=1.0, multiplier=3.0,
                              max_delay_s=5.0, jitter=0.0)
    assert [retry.backoff_delay(k, nojit) for k in (1, 2, 3, 4)] == [
        1.0, 3.0, 5.0, 5.0  # capped at max_delay_s
    ]
    assert retry.TRANSIENT_EXIT_CODES == frozenset({3, 4})


def test_retry_call_recovers_transient_and_respects_budget():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise InjectedFault("drain", "raise")
        return "ok"

    slept = []
    assert retry.retry_call(flaky, policy=FAST,
                            sleep=slept.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2

    def always():
        raise InjectedFault("drain", "raise")

    with pytest.raises(InjectedFault):
        retry.retry_call(always, policy=FAST, sleep=lambda s: None)


def test_retry_call_fatal_raises_immediately():
    calls = []

    def fatal():
        calls.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry.retry_call(fatal, policy=FAST, sleep=lambda s: None)
    assert len(calls) == 1


# ----------------------------------------- sweep supervised recovery

def _chaos_sweep(tmp_path, small_sweep, schedule, name, **kw):
    b, recipe, key = small_sweep
    ck = str(tmp_path / name)
    with inject.armed(schedule):
        out = sweep(key, b, recipe, nreal=16, chunk=4,
                    checkpoint_path=ck, retry_policy=FAST, **kw)
    return out, ck


def _reference(tmp_path, small_sweep):
    b, recipe, key = small_sweep
    ck = str(tmp_path / "ref.npz")
    return sweep(key, b, recipe, nreal=16, chunk=4,
                 checkpoint_path=ck), ck


def test_sweep_recovers_transient_chunk_failure_byte_identical(
    tmp_path, small_sweep
):
    """The chaos gate's core: an injected transient drain failure is
    absorbed by resume-from-sidecar, the result and the consolidated
    checkpoint are byte-identical to the fault-free run, and the retry
    is visible in telemetry."""
    ref, ref_ck = _reference(tmp_path, small_sweep)
    r0 = counter(names.SWEEP_CHUNK_RETRIES).value
    out, ck = _chaos_sweep(tmp_path, small_sweep,
                           "drain:raise@chunk=2", "chaos.npz")
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()
    assert counter(names.SWEEP_CHUNK_RETRIES).value == r0 + 1


def test_sweep_recovers_torn_checkpoint_write(tmp_path, small_sweep):
    """A checkpoint temp file torn mid-write (truncated + raised) is
    retried; the final consolidated checkpoint is byte-identical."""
    ref, ref_ck = _reference(tmp_path, small_sweep)
    out, ck = _chaos_sweep(tmp_path, small_sweep,
                           "checkpoint_write:torn@call=3", "torn.npz")
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_sweep_recovers_injected_stall_via_drain_timeout(
    tmp_path, small_sweep
):
    """A stall longer than the drain deadline trips DrainTimeout, which
    classifies transient and resumes — the wedged-tunnel story, end to
    end, in-process."""
    ref, ref_ck = _reference(tmp_path, small_sweep)
    out, ck = _chaos_sweep(
        tmp_path, small_sweep, "drain:stall=2@chunk=1", "stall.npz",
        drain_timeout_s=0.4,
    )
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_sweep_device_lost_and_sync_loop_site(tmp_path, small_sweep):
    """device_lost is transient; the depth-1 synchronous loop carries
    the same injection sites as the executor."""
    ref, _ = _reference(tmp_path, small_sweep)
    out, _ck = _chaos_sweep(
        tmp_path, small_sweep, "dispatch:device_lost@chunk=1",
        "sync.npz", pipeline_depth=1,
    )
    np.testing.assert_array_equal(out, ref)


def test_sweep_fatal_fault_not_retried(tmp_path, small_sweep):
    b, recipe, key = small_sweep
    with inject.armed("drain:fatal@chunk=1"):
        with pytest.raises(InjectedFault):
            sweep(key, b, recipe, nreal=16, chunk=4,
                  checkpoint_path=str(tmp_path / "fatal.npz"),
                  retry_policy=FAST)
        assert len(inject.fired()) == 1  # one firing, zero retries


def test_sweep_chunk_retries_zero_is_fail_fast(tmp_path, small_sweep):
    b, recipe, key = small_sweep
    with inject.armed("drain:raise@chunk=1"):
        with pytest.raises(InjectedFault):
            sweep(key, b, recipe, nreal=16, chunk=4,
                  checkpoint_path=str(tmp_path / "ff.npz"),
                  chunk_retries=0, retry_policy=FAST)


def test_sweep_budget_is_per_failing_chunk(tmp_path, small_sweep):
    """Two transient failures on DIFFERENT chunks each get a fresh
    budget; a chunk that keeps failing past the budget re-raises."""
    ref, _ = _reference(tmp_path, small_sweep)
    out, _ck = _chaos_sweep(
        tmp_path, small_sweep,
        "drain:raise@chunk=1;drain:raise@chunk=3", "two.npz",
        chunk_retries=1,
    )
    np.testing.assert_array_equal(out, ref)

    b, recipe, key = small_sweep
    with inject.armed("drain:raise@chunk=1x5"):
        with pytest.raises(InjectedFault):
            sweep(key, b, recipe, nreal=16, chunk=4,
                  checkpoint_path=str(tmp_path / "exhaust.npz"),
                  chunk_retries=2, retry_policy=FAST)
        # first try + 2 retries, then the budget is spent
        assert len(inject.fired()) == 3


# ------------------------------------------------- prefetch staging retry

def test_prefetch_retries_transient_staging_once():
    tiles = [np.full((4, 4), k, dtype=np.float64) for k in range(6)]
    r0 = counter(names.CW_STREAM_STAGE_RETRIES).value
    with inject.armed("cw_stream_stage:raise@chunk=2"):
        got = list(prefetch_to_device(iter(tiles), depth=2))
    assert len(got) == 6
    for k, g in enumerate(got):
        np.testing.assert_array_equal(np.asarray(g), tiles[k])
    assert counter(names.CW_STREAM_STAGE_RETRIES).value == r0 + 1


def test_prefetch_second_transient_failure_escalates():
    """p=1 with two firings beats the single in-place retry: the error
    re-raises on the consumer, in order, after earlier tiles."""
    tiles = [np.full((2, 2), k, dtype=np.float64) for k in range(4)]
    got = []
    with inject.armed("cw_stream_stage:raise@p=1x2"):
        with pytest.raises(InjectedFault):
            for g in prefetch_to_device(iter(tiles), depth=1):
                got.append(g)
    assert len(got) == 0  # the first staging failed twice


def test_prefetch_fatal_staging_not_retried():
    tiles = [np.zeros((2, 2)) for _ in range(3)]
    r0 = counter(names.CW_STREAM_STAGE_RETRIES).value
    with inject.armed("cw_stream_stage:fatal@chunk=1"):
        with pytest.raises(InjectedFault):
            list(prefetch_to_device(iter(tiles), depth=2))
    assert counter(names.CW_STREAM_STAGE_RETRIES).value == r0


# ------------------------------------------------- bench-diff contract

def test_chaos_bench_diff_directions():
    """The CHAOS series' leaves classify the way the gate promises —
    retries/rejects/expiries/fault-overhead are costs (lower-better),
    recovered runs a score (higher-better) — and the committed round
    JSON diffs cleanly against itself."""
    from pta_replicator_tpu.obs.regress import bench_diff, metric_direction

    assert metric_direction("chaos.0.chunk_retries") is False
    assert metric_direction("server.rejected") is False
    assert metric_direction("server.deadline_expired") is False
    assert metric_direction("fault_overhead") is False
    assert metric_direction("fault_overhead_s") is False
    assert metric_direction("cw_stream.stage_retries") is False
    assert metric_direction("recovered_runs") is True

    path = os.path.join(os.path.dirname(__file__), "..",
                        "CHAOS_r11_cpu.json")
    assert os.path.exists(path), (
        "CHAOS_r11_cpu.json must be committed with the chaos evidence"
    )
    _table, summary, rc = bench_diff([path, path])
    assert rc == 0 and summary["regressed"] == 0
    assert summary["comparable"] > 10


# ------------------------------------------------------- nan (data corruption)

def test_kind_nan_only_parses_at_the_drain_site():
    """nan needs an in-flight chunk block to poison: drain parses,
    every other site refuses at parse time (a typo'd schedule must not
    silently run fault-free)."""
    assert inject.parse_schedule("drain:nan@chunk=2")[0].kind == "nan"
    for site in ("dispatch", "io_write", "checkpoint_write",
                 "likelihood_batch"):
        with pytest.raises(ValueError, match="only the drain site"):
            inject.parse_schedule(f"{site}:nan@chunk=0")


def test_poison_disarmed_passthrough_and_seeded_determinism():
    """Disarmed, poison() returns the block untouched (same object).
    Armed, the same schedule + seed poisons the SAME single element
    with NaN on a copy — the caller's buffer is never mutated."""
    block = np.arange(24.0, dtype=np.float32).reshape(2, 3, 4)
    assert inject.poison(inject.SITE_DRAIN, block) is block

    poisoned = []
    for _ in range(2):
        inject.arm("drain:nan@chunk=1", seed=7)
        out = inject.poison(inject.SITE_DRAIN, block, chunk=1)
        inject.disarm()
        assert out is not block and np.all(np.isfinite(block))
        poisoned.append(np.flatnonzero(~np.isfinite(out.reshape(-1))))
    assert poisoned[0].size == 1  # exactly one element
    assert np.array_equal(poisoned[0], poisoned[1])  # seeded: same one

    inject.arm("drain:nan@chunk=1", seed=7)
    missed = inject.poison(inject.SITE_DRAIN, block, chunk=0)
    assert missed is block  # wrong chunk: untouched, zero copies


def test_nan_specs_are_poisons_alone_fire_never_raises_them():
    """fire() and poison() are disjoint by kind: a nan spec never
    raises from fire() at its site, and fire()'s call counters ignore
    nan specs — a mixed schedule keeps its raise trigger exact."""
    inject.arm("drain:nan@call=1;drain:raise@call=2", seed=0)
    block = np.ones(8, dtype=np.float32)
    inject.fire(inject.SITE_DRAIN)  # call 1 for raise-spec only
    out = inject.poison(inject.SITE_DRAIN, block)  # call 1 for nan-spec
    assert np.isnan(out).sum() == 1
    with pytest.raises(InjectedFault) as exc:
        inject.fire(inject.SITE_DRAIN)  # call 2: the raise spec
    assert exc.value.kind == "raise"
    # both specs spent: everything passes through now
    assert inject.poison(inject.SITE_DRAIN, block) is block
    inject.fire(inject.SITE_DRAIN)
