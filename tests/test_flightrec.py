"""Flight recorder + bench-regression gate: watchdog, heartbeat
atomicity, SIGTERM postmortems, ring-buffer bounding, bench-diff
verdicts, and the degraded-capture report paths.

CPU-only, fixture-free, and (except one subprocess test) jax-free.
"""
import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
import warnings

import pytest

from pta_replicator_tpu import obs
from pta_replicator_tpu.obs.flightrec import (
    POSTMORTEM_SCHEMA,
    PROGRESS_SCHEMA,
    FlightRecorder,
    StallWarning,
)
from pta_replicator_tpu.obs.regress import (
    SchemaMismatch,
    bench_diff,
    flatten_metrics,
    metric_direction,
)


@pytest.fixture(autouse=True)
def _fresh_globals():
    obs.reset_all()
    yield
    obs.configure(None)
    obs.reset_all()


def _wait_for(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------- heartbeat
def test_heartbeat_written_and_schema_complete(tmp_path):
    rec = FlightRecorder(str(tmp_path), interval_s=0.02,
                         stall_timeout_s=None).start()
    try:
        obs.gauge("sweep.chunks_total").set(4)
        with obs.span("outer"):
            assert _wait_for(
                lambda: (tmp_path / "progress.json").exists()
            )
            hb = json.loads((tmp_path / "progress.json").read_text())
    finally:
        rec.stop()
    for field in PROGRESS_SCHEMA:
        assert field in hb, f"heartbeat missing {field}"
    assert hb["pid"] == os.getpid()
    assert hb["sweep"]["chunks_total"] == 4
    # final heartbeat after stop() is marked finished
    hb = json.loads((tmp_path / "progress.json").read_text())
    assert hb["finished"] is True


def test_heartbeat_valid_json_under_concurrent_reads(tmp_path):
    """Atomic-replace contract: a reader polling progress.json in a tight
    loop while the sampler rewrites it at high frequency must never see
    a torn/partial document."""
    rec = FlightRecorder(str(tmp_path), interval_s=0.001,
                         stall_timeout_s=None).start()
    path = tmp_path / "progress.json"
    assert _wait_for(path.exists)
    failures = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                doc = json.loads(path.read_text())
                if "written_at" not in doc:
                    failures.append("incomplete doc")
            except json.JSONDecodeError as exc:
                failures.append(repr(exc))
            except FileNotFoundError:
                failures.append("file vanished")

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    while time.monotonic() - t0 < 1.0:
        with obs.span("busy"):
            pass
    stop.set()
    for t in threads:
        t.join()
    rec.stop()
    assert not failures, failures[:5]


def test_heartbeat_eta_from_chunk_progress(tmp_path):
    rec = FlightRecorder(str(tmp_path), interval_s=0.01,
                         stall_timeout_s=None).start()
    try:
        obs.gauge("sweep.chunks_total").set(100)
        for i in range(5):
            obs.gauge("sweep.chunks_done").set(i + 1)
            time.sleep(0.03)

        def has_eta():
            try:
                hb = json.loads((tmp_path / "progress.json").read_text())
            except (FileNotFoundError, json.JSONDecodeError):
                return False
            return "eta_s" in hb["sweep"] and "chunk_rate_per_s" in hb["sweep"]

        assert _wait_for(has_eta)
        hb = json.loads((tmp_path / "progress.json").read_text())
        assert hb["sweep"]["chunks_done"] == 5
        assert hb["sweep"]["eta_s"] > 0
    finally:
        rec.stop()


# ----------------------------------------------------------- watchdog
def test_watchdog_fires_once_per_stall_episode(tmp_path):
    rec = FlightRecorder(str(tmp_path), interval_s=0.02,
                         stall_timeout_s=0.15).start()
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with obs.span("wedged_stage"):
                time.sleep(0.6)  # several watchdog periods past deadline
        stalls = [w for w in caught
                  if issubclass(w.category, StallWarning)]
        assert len(stalls) == 1, [str(w.message) for w in stalls]
        assert "wedged_stage" in str(stalls[0].message)
        assert obs.counter("flightrec.stalls").value == 1
        # the stall is also a tracer event (-> ring buffer + events.jsonl)
        assert any(
            r["type"] == "event" and r["name"] == "flightrec.stall"
            for r in rec.ring
        )
        # activity re-arms the watchdog: a second quiet period warns again
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with obs.span("alive_again"):
                pass
            time.sleep(0.45)
        assert sum(
            1 for w in caught if issubclass(w.category, StallWarning)
        ) == 1
        assert obs.counter("flightrec.stalls").value == 2
    finally:
        rec.stop()


def test_no_stall_while_spans_flow(tmp_path):
    rec = FlightRecorder(str(tmp_path), interval_s=0.02,
                         stall_timeout_s=0.3).start()
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.7:
                with obs.span("tick"):
                    time.sleep(0.01)
        assert not [w for w in caught
                    if issubclass(w.category, StallWarning)]
        assert obs.counter("flightrec.stalls").value == 0
    finally:
        rec.stop()


# -------------------------------------------------------- ring buffer
def test_ring_buffer_bounded_and_keeps_newest(tmp_path):
    rec = FlightRecorder(str(tmp_path), interval_s=5.0, ring_size=16,
                         stall_timeout_s=None).start()
    try:
        for i in range(100):
            with obs.span("s", i=i):
                pass
    finally:
        rec.stop()
    assert len(rec.ring) == 16
    assert [r["attrs"]["i"] for r in rec.ring] == list(range(84, 100))


# --------------------------------------------------------- postmortem
def test_postmortem_written_once_with_ring_and_metrics(tmp_path):
    rec = FlightRecorder(str(tmp_path), interval_s=5.0, ring_size=8,
                         stall_timeout_s=None).start()
    try:
        obs.counter("sweep.realizations").inc(64)
        with obs.span("doomed"):
            pass
        path = rec.write_postmortem(
            "exception", exc=RuntimeError("boom")
        )
        # second call must not overwrite the first report
        before = open(path).read()
        rec.write_postmortem("SIGTERM")
        assert open(path).read() == before
    finally:
        rec.stop()
    pm = json.loads((tmp_path / "postmortem.json").read_text())
    for field in POSTMORTEM_SCHEMA:
        assert field in pm
    assert pm["reason"] == "exception"
    assert pm["exception"]["type"] == "RuntimeError"
    assert any(r.get("path") == "doomed" for r in pm["ring"])
    assert pm["metrics"]["sweep.realizations"][0]["value"] == 64


SIGTERM_CHILD = r"""
import sys, time
from pta_replicator_tpu import obs
obs.start_capture(sys.argv[1], heartbeat_interval_s=0.02)
with obs.span("realize"):
    with obs.span("compute"):
        obs.gauge("sweep.chunks_total").set(50)
        for i in range(5000):
            with obs.span("sweep_chunk", chunk=i):
                time.sleep(0.005)
            obs.gauge("sweep.chunks_done").set(i + 1)
"""


def test_postmortem_on_injected_sigterm(tmp_path):
    """The acceptance rehearsal: SIGTERM a captured run mid-sweep, the
    black box lands with the in-flight spans in ring + open stacks."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = subprocess.Popen(
        [sys.executable, "-c", SIGTERM_CHILD, str(tmp_path)],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    try:
        assert _wait_for(
            lambda: (tmp_path / "progress.json").exists(), timeout=30
        ), child.stderr.read() if child.poll() is not None else "no heartbeat"
        time.sleep(0.3)  # let some chunks land in the ring
        child.send_signal(signal.SIGTERM)
        rc = child.wait(timeout=15)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()
    assert rc == -signal.SIGTERM  # default disposition re-delivered
    pm = json.loads((tmp_path / "postmortem.json").read_text())
    assert pm["reason"] == "SIGTERM"
    assert any(
        r.get("path") == "realize/compute/sweep_chunk" for r in pm["ring"]
    )
    stacks = list(pm["heartbeat"]["open_spans"].values())
    assert ["realize", "compute"] in [s[:2] for s in stacks]
    # events.jsonl was flushed alongside the postmortem
    assert "sweep_chunk" in (tmp_path / "events.jsonl").read_text()


def test_emergency_postmortem_with_tracer_lock_held(tmp_path):
    """The deadlock the SIGTERM flush must survive: the signal lands
    while the interrupted frame is inside ``Tracer._record``'s critical
    section (the sink write runs under the tracer lock), so the lock is
    held by a thread that cannot run until the handler returns. The
    emergency flush must still land the complete black box — bounded
    acquires, unlocked fallback — instead of timing out postmortem-less
    after the handler deadline."""
    from pta_replicator_tpu.obs.trace import TRACER

    obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
    try:
        with obs.span("realize"):
            with obs.span("compute"):
                rec = obs.flightrec.active()
                assert TRACER._lock.acquire(timeout=5)
                try:
                    t0 = time.monotonic()
                    path = rec.write_postmortem("SIGTERM", emergency=True)
                    took = time.monotonic() - t0
                finally:
                    TRACER._lock.release()
        pm = json.loads(open(path).read())
        assert pm["reason"] == "SIGTERM"
        stacks = list(pm["heartbeat"]["open_spans"].values())
        assert ["realize", "compute"] in [s[:2] for s in stacks]
        # bounded: two 1s lock timeouts at most, nowhere near the 5s
        # handler deadline that previously expired postmortem-less
        assert took < 4.0
    finally:
        obs.finish_capture()


def test_emergency_postmortem_with_registry_lock_held(tmp_path):
    """Sibling of the tracer-lock deadlock: the signal may equally land
    while the interrupted frame is inside ``MetricsRegistry._get``'s
    critical section (sweep-loop gauge lookups run every chunk), so the
    registry lock — hit by ``_metric_value``, the occupancy gauge
    mirror, and ``REGISTRY.to_json`` — can never be released either.
    The emergency flush must bound those acquires too."""
    from pta_replicator_tpu.obs.metrics import REGISTRY

    obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
    try:
        obs.gauge("sweep.chunks_done").set(3.0)
        with obs.span("realize"):
            rec = obs.flightrec.active()
            assert REGISTRY._lock.acquire(timeout=5)
            try:
                t0 = time.monotonic()
                path = rec.write_postmortem("SIGTERM", emergency=True)
                took = time.monotonic() - t0
            finally:
                REGISTRY._lock.release()
        pm = json.loads(open(path).read())
        assert pm["reason"] == "SIGTERM"
        # the unlocked fallback still reads the live metric values
        assert pm["heartbeat"]["sweep"]["chunks_done"] == 3.0
        assert pm["metrics"]["sweep.chunks_done"][0]["value"] == 3.0
        assert took < 4.0
    finally:
        obs.finish_capture()


def test_emergency_postmortem_with_occupancy_lock_held(tmp_path):
    """Third lock in the emergency hazard set: the pipeline dispatcher
    records busy intervals on the calling (main) thread, so the signal
    can land inside ``StageOccupancy.observe``'s critical section."""
    obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
    try:
        with obs.span("realize"):
            rec = obs.flightrec.active()
            assert rec.occupancy._lock.acquire(timeout=5)
            try:
                t0 = time.monotonic()
                path = rec.write_postmortem("SIGTERM", emergency=True)
                took = time.monotonic() - t0
            finally:
                rec.occupancy._lock.release()
        pm = json.loads(open(path).read())
        assert pm["reason"] == "SIGTERM"
        assert "occupancy" in pm["heartbeat"]
        assert took < 4.0
    finally:
        obs.finish_capture()


def test_finish_capture_writes_postmortem_on_exception(tmp_path):
    with pytest.raises(RuntimeError):
        obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
        try:
            with obs.span("stage"):
                raise RuntimeError("mid-run failure")
        finally:
            obs.finish_capture()
    pm = json.loads((tmp_path / "postmortem.json").read_text())
    assert pm["reason"] == "exception"
    assert pm["exception"]["message"] == "mid-run failure"
    # the normal capture artifacts were still written
    assert (tmp_path / "metrics.json").exists()


def test_recapture_clears_previous_runs_black_box(tmp_path):
    """bench.py's OOM retry ladder reruns into the same telemetry dir:
    the crashed attempt's postmortem/heartbeat must not make watch and
    report misreport the healthy retry as dead."""
    obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
    obs.flightrec.active().write_postmortem("exception",
                                            exc=RuntimeError("oom"))
    obs.finish_capture()
    assert (tmp_path / "postmortem.json").exists()

    obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
    assert not (tmp_path / "postmortem.json").exists()
    assert not (tmp_path / "progress.json").exists()
    from pta_replicator_tpu.obs.report import watch_progress

    buf = io.StringIO()
    assert watch_progress(str(tmp_path), once=True, file=buf) == 3
    assert "postmortem" not in buf.getvalue()
    obs.finish_capture()


def test_clean_finish_leaves_no_postmortem(tmp_path):
    obs.start_capture(str(tmp_path), heartbeat_interval_s=5.0)
    with obs.span("stage"):
        pass
    obs.finish_capture()
    assert not (tmp_path / "postmortem.json").exists()
    hb = json.loads((tmp_path / "progress.json").read_text())
    assert hb["finished"] is True


# ------------------------------------------------- degraded report paths
def test_report_no_data_and_corrupt_artifacts(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    empty = tmp_path / "empty"
    empty.mkdir()
    main(["report", str(empty)])
    out = capsys.readouterr().out
    assert "no telemetry data" in out

    # metrics.json truncated mid-write by a kill: degrade, don't raise
    partial = tmp_path / "partial"
    partial.mkdir()
    (partial / "metrics.json").write_text('{"sweep.realizations": [{"k')
    main(["report", str(partial)])
    out = capsys.readouterr().out
    assert "metrics.json: unreadable" in out

    # metrics-only capture renders its metrics section
    monly = tmp_path / "monly"
    monly.mkdir()
    (monly / "metrics.json").write_text(json.dumps(
        {"sweep.realizations": [
            {"kind": "counter", "labels": {}, "value": 5}
        ]}
    ))
    main(["report", str(monly)])
    assert "sweep.realizations = 5" in capsys.readouterr().out


def test_finish_capture_without_start_is_noop():
    assert obs.finish_capture() is None


def test_postmortem_cli_without_postmortem(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    main(["postmortem", str(tmp_path)])
    assert "no postmortem.json" in capsys.readouterr().out


def test_watch_once_exit_codes(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main
    from pta_replicator_tpu.obs.report import watch_progress

    # nothing to read -> exit 3
    with pytest.raises(SystemExit) as exc:
        main(["watch", str(tmp_path), "--once"])
    assert exc.value.code == 3
    capsys.readouterr()

    rec = FlightRecorder(str(tmp_path), interval_s=5.0,
                         stall_timeout_s=None)
    rec.write_heartbeat()
    buf = io.StringIO()
    assert watch_progress(str(tmp_path), once=True, file=buf) == 0
    assert "idle" in buf.getvalue()

    # a postmortem turns watch into exit 2 with a pointer
    rec.write_postmortem("SIGTERM")
    buf = io.StringIO()
    assert watch_progress(str(tmp_path), once=True, file=buf) == 2
    assert "postmortem" in buf.getvalue()


def test_report_surfaces_stalls_and_postmortem(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    obs.start_capture(str(tmp_path), heartbeat_interval_s=0.02,
                      stall_timeout_s=0.1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with obs.span("wedge"):
            time.sleep(0.35)
    rec = obs.flightrec.active()
    rec.write_postmortem("SIGTERM")
    rec.stop(finished=False)
    obs.configure(None)

    main(["report", str(tmp_path)])
    out = capsys.readouterr().out
    assert "STALLS" in out
    assert "POSTMORTEM present" in out

    main(["postmortem", str(tmp_path)])
    out = capsys.readouterr().out
    assert "reason: SIGTERM" in out
    assert "final heartbeat" in out


# ------------------------------------------------------ schema checker
def test_schema_checker_validates_flightrec_artifacts(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)

    # in-process sample generation (also runs in no-arg main())
    for path, kind in checker.generate_flightrec_sample(str(tmp_path)):
        assert checker.validate_flightrec_file(path, kind) == []

    # a progress.json missing required fields is flagged
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "progress.json").write_text('{"schema": 1}')
    (bad / "events.jsonl").write_text('{"type": "meta", "schema": 1, '
                                      '"t0": 1.0}\n')
    assert checker.main([str(bad)]) == 1


# ----------------------------------------------------------- bench-diff
def _bench_doc(value, elapsed, extra=None):
    doc = {
        "metric": "realizations/s", "value": value,
        "unit": "realizations/s", "schema_version": 2,
        "git_rev": "abc1234",
        "platform": {"python": "3.11", "os": "linux"},
        "measure_elapsed_s": elapsed,
    }
    doc.update(extra or {})
    return doc


def test_bench_diff_verdicts_on_synthetic_regression(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc(1000.0, 4.0)))
    # value -30% (regression), elapsed +40% (regression for a duration)
    b.write_text(json.dumps(_bench_doc(700.0, 5.6)))
    table, summary, rc = bench_diff([str(a), str(b)], threshold=0.10)
    assert rc == 1
    assert summary["verdicts"]["value"] == "regressed"
    assert summary["verdicts"]["measure_elapsed_s"] == "regressed"
    assert "regressed" in table

    # improvement: faster rate, shorter elapsed -> rc 0
    b.write_text(json.dumps(_bench_doc(1500.0, 2.6)))
    _table, summary, rc = bench_diff([str(a), str(b)], threshold=0.10)
    assert rc == 0
    assert summary["verdicts"]["value"] == "improved"
    assert summary["verdicts"]["measure_elapsed_s"] == "improved"

    # within the warn band (6% with threshold 10%): warn, still rc 0
    b.write_text(json.dumps(_bench_doc(940.0, 4.0)))
    _table, summary, rc = bench_diff([str(a), str(b)], threshold=0.10)
    assert rc == 0
    assert summary["verdicts"]["value"] == "warn"
    assert summary["verdicts"]["measure_elapsed_s"] == "ok"


def test_bench_diff_unwraps_driver_shape_and_null_parsed(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({
        "n": 2, "cmd": "python bench.py", "rc": 0, "tail": "...",
        "parsed": _bench_doc(1000.0, 4.0),
    }))
    b.write_text(json.dumps(_bench_doc(500.0, 8.0)))
    _table, summary, rc = bench_diff([str(a), str(b)], threshold=0.10)
    assert rc == 1 and summary["verdicts"]["value"] == "regressed"

    # a round whose parsed is null (chip unreachable): degrade, exit 2
    a.write_text(json.dumps({"n": 1, "cmd": "x", "rc": 1,
                             "tail": "err", "parsed": None}))
    table, summary, rc = bench_diff([str(a), str(b)])
    assert rc == 2 and summary["comparable"] == 0
    assert "nothing comparable" in table


def test_bench_diff_refuses_newer_schema(tmp_path):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc(1.0, 1.0)))
    b.write_text(json.dumps(
        _bench_doc(1.0, 1.0, {"schema_version": 99})
    ))
    with pytest.raises(SchemaMismatch, match="schema_version 99"):
        bench_diff([str(a), str(b)])


def test_bench_diff_cli_exit_codes(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc(1000.0, 4.0)))
    b.write_text(json.dumps(_bench_doc(100.0, 40.0)))
    with pytest.raises(SystemExit) as exc:
        main(["bench-diff", str(a), str(b)])
    assert exc.value.code == 1
    assert "regressed" in capsys.readouterr().out

    b.write_text(json.dumps(_bench_doc(1001.0, 3.99)))
    main(["bench-diff", str(a), str(b)])  # no regression: returns None
    assert "0 regressed" in capsys.readouterr().out


def test_flatten_and_direction_classification():
    flat = flatten_metrics({
        "value": 2.0,
        "schema_version": 2,            # provenance: skipped
        "timestamp": "2026-01-01",      # skipped
        "platform": {"python": "3.11"},  # skipped prefix
        "ok_flag": True,                 # bool: skipped
        "telemetry": {"spans": {"measure": {"total_s": 0.5, "calls": 1}}},
    })
    assert flat["value"] == 2.0
    assert flat["telemetry.spans.measure.total_s"] == 0.5
    assert "schema_version" not in flat
    assert "platform.python" not in flat
    assert "ok_flag" not in flat

    assert metric_direction("value") is True
    assert metric_direction("speedup_vs_cpu_oracle") is True
    # throughput names end in _s too — they must NOT read as durations
    # (that would invert the gate: a collapse would report "improved")
    assert metric_direction("cpu_oracle_real_per_s") is True
    assert metric_direction("achieved_tflops_per_s") is True
    assert metric_direction("rate_real_per_s") is True
    assert metric_direction("measure_elapsed_s") is False
    assert metric_direction("cgw_scan_ms") is False
    assert metric_direction("telemetry.spans.measure.total_s") is False
    assert metric_direction("bench_chunk") is None

    from pta_replicator_tpu.obs.regress import classify

    # a halved throughput is a regression even though the name ends _s
    verdict, rel = classify(10.0, 5.0,
                            metric_direction("rate_real_per_s"), 0.10)
    assert verdict == "regressed" and rel == -0.5
