"""Direct unit coverage for the GLS building blocks (ISSUE 9
satellite): timing/fit.py's gls_fit jitter / rank-deficiency branches
and models/batched.py's gls_fit_uncertainties + gls_fit_subtract,
each against a dense numpy oracle — fixture-free (synthetic batches;
the reference-tree integration test in test_batched.py only runs where
/root/reference exists)."""
import numpy as np
import pytest

import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models import batched as B
from pta_replicator_tpu.timing.fit import gls_fit, wls_fit


def _dense_system(n=40, k=3, seed=0, dup_col=False, zero_col=False):
    rng = np.random.default_rng(seed)
    t = np.linspace(0.0, 1.0, n)
    cols = [np.ones_like(t), t, t**2][:k]
    if dup_col:
        cols.append(t.copy())  # exactly collinear column
    if zero_col:
        cols.append(np.zeros_like(t))
    M = np.stack(cols, axis=-1)
    L = rng.standard_normal((n, n)) * 0.1
    C = L @ L.T + np.diag(rng.uniform(0.5, 2.0, n))
    r = rng.standard_normal(n)
    return r, C, M


def _oracle_gls(r, C, M, jitter=0.0):
    """p = (M^T C^-1 M)^+ M^T C^-1 r via explicit dense algebra."""
    Cj = C + jitter * np.eye(C.shape[0])
    Ci = np.linalg.inv(Cj)
    A = M.T @ Ci @ M
    p = np.linalg.pinv(A) @ (M.T @ Ci @ r)
    return p, r - M @ p, np.linalg.pinv(A)


def test_gls_fit_matches_dense_oracle():
    r, C, M = _dense_system()
    p, post = gls_fit(r, C, M)
    p_ref, post_ref, _ = _oracle_gls(r, C, M)
    np.testing.assert_allclose(p, p_ref, rtol=1e-9)
    np.testing.assert_allclose(post, post_ref, rtol=0, atol=1e-10)


def test_gls_fit_jitter_branch():
    """The jitter regularizes a singular covariance: without it the
    Cholesky fails; with it the fit matches the oracle at C + jI."""
    r, C, M = _dense_system()
    C_sing = C.copy()
    C_sing[:] = 0.0  # rank-0: raw Cholesky must fail
    with pytest.raises(np.linalg.LinAlgError):
        gls_fit(r, C_sing, M)
    p, post = gls_fit(r, C_sing, M, jitter=0.5)
    p_ref, post_ref, _ = _oracle_gls(r, C_sing, M, jitter=0.5)
    np.testing.assert_allclose(p, p_ref, rtol=1e-9)
    np.testing.assert_allclose(post, post_ref, rtol=0, atol=1e-10)
    # jitter on a healthy C matches the jittered oracle too (the
    # branch composes, it doesn't replace)
    p2, _ = gls_fit(r, C, M, jitter=0.1)
    p2_ref, _, _ = _oracle_gls(r, C, M, jitter=0.1)
    np.testing.assert_allclose(p2, p2_ref, rtol=1e-9)


def test_gls_fit_return_cov_matches_oracle():
    r, C, M = _dense_system()
    p, _post, pcov = gls_fit(r, C, M, return_cov=True)
    _p_ref, _pr, pcov_ref = _oracle_gls(r, C, M)
    np.testing.assert_allclose(pcov, pcov_ref, rtol=1e-8)


def test_gls_fit_zero_column_branch():
    """An all-zero design column (the padding convention) must yield a
    zero parameter and zero variance instead of raising — the
    _normalized_lstsq norms==0 branch."""
    r, C, M = _dense_system(zero_col=True)
    p, post, pcov = gls_fit(r, C, M, return_cov=True)
    assert p[-1] == 0.0
    assert pcov[-1, -1] == 0.0
    p_ref, post_ref, _ = _oracle_gls(r, C, M[:, :-1])
    np.testing.assert_allclose(p[:-1], p_ref, rtol=1e-9)
    np.testing.assert_allclose(post, post_ref, rtol=0, atol=1e-10)


def test_wls_fit_zero_error_guard_matches_oracle():
    rng = np.random.default_rng(3)
    n = 30
    t = np.linspace(0, 1, n)
    M = np.stack([np.ones_like(t), t], axis=-1)
    sigma = rng.uniform(0.5, 2.0, n)
    r = rng.standard_normal(n)
    p, post = wls_fit(r, sigma, M)
    Ci = np.diag(1.0 / sigma**2)
    A = M.T @ Ci @ M
    p_ref = np.linalg.solve(A, M.T @ Ci @ r)
    np.testing.assert_allclose(p, p_ref, rtol=1e-9)
    np.testing.assert_allclose(post, r - M @ p_ref, atol=1e-12)


# ---------------------- batched GLS vs dense oracle (fixture-free) ---

@pytest.fixture(scope="module")
def gls_setup():
    batch = synthetic_batch(
        npsr=5, ntoa=160, nbackend=2, seed=4, dtype=jnp.float64
    )
    nb = len(batch.backend_names)
    rng = np.random.default_rng(8)
    recipe = B.Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.4, (batch.npsr, nb))),
        log10_equad=jnp.asarray(rng.uniform(-6.8, -6.2, (batch.npsr, nb))),
        log10_ecorr=jnp.asarray(rng.uniform(-6.9, -6.4, (batch.npsr, nb))),
        rn_log10_amplitude=jnp.asarray(
            rng.uniform(-13.8, -13.2, batch.npsr)
        ),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, batch.npsr)),
        rn_nmodes=12,
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        gwb_gls_nmodes=10,
    )
    t = np.asarray(batch.toas_s)
    scale = np.asarray(batch.tspan_s)[:, None]
    design = np.stack(
        [np.ones_like(t), t / scale, (t / scale) ** 2,
         np.zeros_like(t)],  # padding column
        axis=-1,
    )
    return batch, recipe, design


def _dense_cov(batch, recipe, p):
    """Dense per-pulsar C from the same gls_noise_model components the
    device path consumes (the components themselves are pinned against
    the enterprise-convention oracle in test_batched.py)."""
    sigma2, ecorr2, U, phi = B.gls_noise_model(batch, recipe)
    sigma2 = np.asarray(sigma2, np.float64)
    C = np.diag(sigma2[p])
    if ecorr2 is not None:
        ec = np.asarray(ecorr2, np.float64)
        idx = np.asarray(batch.epoch_index)[p]
        onehot = (idx[:, None] == np.arange(ec.shape[1])[None, :])
        onehot = onehot.astype(np.float64)
        C = C + (onehot * ec[p][None, :]) @ onehot.T
    if U is not None:
        Up = np.asarray(U, np.float64)[p]
        ph = np.asarray(phi, np.float64)[p]
        C = C + (Up * ph[None, :]) @ Up.T
    return C


def test_gls_fit_uncertainties_match_dense_oracle(gls_setup):
    """sqrt(diag((M^T C^-1 M)^-1)) from the nested-Woodbury device
    path == the explicit dense inverse, per pulsar; padding columns
    report exactly 0."""
    batch, recipe, design = gls_setup
    sig = np.asarray(B.gls_fit_uncertainties(batch, design, recipe))
    for p in range(batch.npsr):
        C = _dense_cov(batch, recipe, p)
        Ci = np.linalg.inv(C)
        M = design[p][:, :3]  # the real columns
        A = M.T @ Ci @ M
        ref = np.sqrt(np.diag(np.linalg.inv(A)))
        np.testing.assert_allclose(sig[p][:3], ref, rtol=1e-6)
        assert sig[p][3] == 0.0  # padding column


def test_gls_fit_subtract_matches_dense_oracle(gls_setup):
    """The C^-1-weighted projection (never materializing C) == the
    dense GLS projection, per pulsar — the fixture-free twin of
    test_batched.py's reference-tree integration test, protecting the
    white_ecorr_solver refactor."""
    batch, recipe, design = gls_setup
    rng = np.random.default_rng(11)
    delays = jnp.asarray(
        rng.standard_normal(np.asarray(batch.toas_s).shape) * 1e-6
    ) * batch.mask
    post = np.asarray(B.gls_fit_subtract(delays, batch, design, recipe))
    for p in range(batch.npsr):
        C = _dense_cov(batch, recipe, p)
        Ci = np.linalg.inv(C)
        M = design[p][:, :3]
        r = np.asarray(delays, np.float64)[p]
        coef = np.linalg.solve(M.T @ Ci @ M, M.T @ Ci @ r)
        ref = r - M @ coef
        num = np.sqrt(np.mean((post[p] - ref) ** 2))
        den = np.sqrt(np.mean(ref**2))
        # 1e-6 like the reference-tree twin: the device path carries a
        # deliberate 1e-10 ridge the plain dense solve does not
        assert num / den < 1e-6, (p, num / den)


def test_gls_fit_subtract_ridge_breaks_collinearity(gls_setup):
    """Exactly duplicated design columns: the ridge turns a singular
    normal system into a deterministic even split instead of NaNs."""
    batch, recipe, design = gls_setup
    dup = np.concatenate([design[:, :, :3], design[:, :, 1:2]], axis=-1)
    rng = np.random.default_rng(12)
    delays = jnp.asarray(
        rng.standard_normal(np.asarray(batch.toas_s).shape) * 1e-6
    ) * batch.mask
    post = np.asarray(B.gls_fit_subtract(delays, batch, dup, recipe))
    assert np.isfinite(post).all()
    # the projection is the same subspace: residual equals the
    # non-duplicated fit to float tolerance
    ref = np.asarray(B.gls_fit_subtract(delays, batch,
                                        design[:, :, :3], recipe))
    np.testing.assert_allclose(post, ref, rtol=0, atol=1e-12)
