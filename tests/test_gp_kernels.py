"""ops/pallas_gp.py + the raw-speed ladder (docs/performance.md):
interpret-mode bit-identity between the Pallas kernels and their
tiled-XLA fallbacks at f32 AND f64, fused-vs-composed agreement at f64
round-off, the numerics-gated bf16 refusal/acceptance contract, the
tile autotuner's cache degradation ladder, and the default-path
bitwise pin. Fixture-free (synthetic batches), CPU-only."""
import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.covariance import kernels as cov_kernels
from pta_replicator_tpu.likelihood import gp, infer, tuner
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.obs import numerics
from pta_replicator_tpu.ops import pallas_gp


def _recipe(batch, seed=0):
    nb = len(batch.backend_names)
    rng = np.random.default_rng(seed)
    return Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.4, (batch.npsr, nb))),
        log10_equad=jnp.asarray(
            rng.uniform(-6.8, -6.2, (batch.npsr, nb))
        ),
        log10_ecorr=jnp.asarray(
            rng.uniform(-6.9, -6.4, (batch.npsr, nb))
        ),
        rn_log10_amplitude=jnp.asarray(
            rng.uniform(-13.8, -13.2, batch.npsr)
        ),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, batch.npsr)),
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        rn_nmodes=8,
        gwb_gls_nmodes=6,
    )


@pytest.fixture(scope="module")
def setup():
    batch = synthetic_batch(
        npsr=6, ntoa=180, nbackend=2, seed=3, dtype=jnp.float64
    )
    recipe = _recipe(batch)
    rng = np.random.default_rng(11)
    res = jnp.asarray(
        rng.standard_normal(batch.toas_s.shape) * 1e-6
    ) * batch.mask
    return batch, recipe, res


_GRID = {"rn_log10_amplitude": np.linspace(-14.0, -13.4, 4)}


def _woodbury_operands(dtype, npsr=3, nt=100, q=7, seed=2):
    rng = np.random.default_rng(seed)
    T = jnp.asarray(rng.standard_normal((npsr, nt, q)), dtype)
    mask = rng.random((npsr, nt)) > 0.1
    w = jnp.asarray(rng.uniform(0.5, 2.0, (npsr, nt)) * mask, dtype)
    r = jnp.asarray(rng.standard_normal((npsr, nt)) * mask, dtype)
    return T, w, r


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_fused_woodbury_interpret_bit_identical(dtype):
    """The one-tile-implementation contract: the Pallas kernel under
    interpret mode and the tiled-XLA scan produce byte-identical
    accumulators at f32 AND f64 (same tile fn, same zero-init, same
    sequential order — nothing left to round differently)."""
    T, w, r = _woodbury_operands(dtype)
    ref = pallas_gp.fused_woodbury_xla(T, w, r, tile=32)
    ker = pallas_gp.fused_woodbury_update(T, w, r, tile=32,
                                          interpret=True)
    for a, b in zip(ref, ker):
        assert a.dtype == dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_woodbury_tile_padding_exact():
    """A tile that does not divide Nt zero-pads with w=0 rows — the
    ragged grid must agree with the divisible grid to f64 round-off."""
    T, w, r = _woodbury_operands(jnp.float64, nt=97)
    a = pallas_gp.fused_woodbury_xla(T, w, r, tile=32)
    b = pallas_gp.fused_woodbury_xla(T, w, r, tile=97)
    for x, y in zip(a, b):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-13, atol=1e-15
        )


def _tridiag_operands(dtype, npsr=2, nb=5, b=4, q=3, seed=4):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((npsr, nb, b, b))
    D = jnp.asarray(
        A @ np.swapaxes(A, -1, -2) + 6.0 * np.eye(b), dtype
    )
    E = jnp.asarray(
        0.2 * rng.standard_normal((npsr, nb - 1, b, b)), dtype
    )
    X = jnp.asarray(rng.standard_normal((npsr, nb, b, q)), dtype)
    return D, E, X


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_tridiag_interpret_bit_identical(dtype):
    """Same contract for the block-tridiagonal factor+solve kernel:
    interpret-mode Pallas == XLA scan, byte for byte, both dtypes."""
    D, E, X = _tridiag_operands(dtype)
    ref = pallas_gp.tridiag_factor_solve_xla(D, E, X)
    ker = pallas_gp.tridiag_factor_solve(D, E, X, interpret=True)
    for a, b in zip(ref, ker):
        assert a.dtype == dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_block_tridiag_factor_solve_matches_composed():
    """covariance/kernels.py::block_tridiag_factor_solve: the fused
    xla/pallas_interpret backends agree with the composed
    cholesky+solve scan reference, and the solve is correct against a
    dense reconstruction."""
    D, E, X = _tridiag_operands(jnp.float64)
    Ld0, M0, Z0 = cov_kernels.block_tridiag_factor_solve(
        D, E, X, backend="scan"
    )
    for backend in ("xla", "pallas_interpret"):
        Ld, M, Z = cov_kernels.block_tridiag_factor_solve(
            D, E, X, backend=backend
        )
        np.testing.assert_allclose(np.asarray(Z), np.asarray(Z0),
                                   rtol=1e-12, atol=1e-14)
        np.testing.assert_allclose(np.asarray(Ld), np.asarray(Ld0),
                                   rtol=1e-12, atol=1e-14)
    # dense correctness: assemble C and check C @ Z == X
    npsr, nb, b, _ = D.shape
    n = nb * b
    C = np.zeros((npsr, n, n))
    for k in range(nb):
        C[:, k * b:(k + 1) * b, k * b:(k + 1) * b] = np.asarray(D[:, k])
        if k:
            Ek = np.asarray(E[:, k - 1])
            C[:, k * b:(k + 1) * b, (k - 1) * b:k * b] = Ek
            C[:, (k - 1) * b:k * b, k * b:(k + 1) * b] = np.swapaxes(
                Ek, -1, -2
            )
    Zf = np.asarray(Z0).reshape(npsr, n, -1)
    Xf = np.asarray(X).reshape(npsr, n, -1)
    np.testing.assert_allclose(C @ Zf, Xf, rtol=1e-9, atol=1e-11)
    with pytest.raises(ValueError):
        cov_kernels.block_tridiag_factor_solve(D, E, X, backend="nope")


def test_fused_build_matches_composed(setup):
    """Rung 1 acceptance: the fused ReducedGP build agrees with the
    composed build to f64 round-off (<= 1e-12 relative) on the grid
    driver, and the fused bank driver agrees with the composed one."""
    batch, recipe, res = setup
    ll = np.asarray(
        infer.grid_loglikelihood(res, batch, recipe, _GRID)
    )
    llf = np.asarray(
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, backend="xla"
        )
    )
    np.testing.assert_allclose(llf, ll, rtol=1e-12)
    bank = jnp.stack([res, 0.5 * res])
    bll = np.asarray(infer.bank_loglikelihood(bank, batch, recipe))
    bllf = np.asarray(
        infer.bank_loglikelihood(
            bank, batch, recipe, fused=True, backend="xla"
        )
    )
    np.testing.assert_allclose(bllf, bll, rtol=1e-12)


def test_fused_interpret_backend_matches_xla(setup):
    """The interpret backend threads the Pallas kernel through the
    whole build — same numbers as the xla backend end to end."""
    batch, recipe, res = setup
    a = np.asarray(
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, backend="xla"
        )
    )
    b = np.asarray(
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True,
            backend="pallas_interpret",
        )
    )
    np.testing.assert_allclose(b, a, rtol=1e-14)


def test_default_path_bitwise_pin(setup):
    """The ladder is opt-in: the default driver call is byte-identical
    to an explicit fused=False call, and the default build still
    produces the composed projector (CiT materialized, fused flag
    off) — no new kernel on the path nobody asked to change."""
    batch, recipe, res = setup
    a = np.asarray(infer.grid_loglikelihood(res, batch, recipe, _GRID))
    b = np.asarray(
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=False,
            precision="highest", tile=None, backend="auto",
        )
    )
    np.testing.assert_array_equal(a, b)
    reduced = gp.ReducedGP.build(batch, recipe)
    assert reduced.fused is False
    assert reduced.CiT is not None


def test_bf16_refused_without_verdict(setup):
    """Rung 2's gate: precision='bf16' without a numerics capture (or
    with a capture that never saw the fused sites) raises
    PrecisionNotReady — never silently computes in bf16."""
    batch, recipe, res = setup
    with pytest.raises(gp.PrecisionNotReady):
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, precision="bf16"
        )
    with pytest.raises(gp.PrecisionNotReady):
        gp.require_precision_ready("bf16", None)
    with pytest.raises(ValueError):
        gp.require_precision_ready("fp8")
    assert gp.require_precision_ready(None) == "highest"
    assert gp.require_precision_ready("highest") == "highest"


def test_bf16_refused_on_unready_capture(tmp_path, setup):
    """A capture file that exists but lacks ready verdicts for the
    fused sites is refused with the sites named in the message."""
    batch, recipe, res = setup
    (tmp_path / "numerics.json").write_text(json.dumps(
        {"schema": 0, "sites": {}}
    ))
    with pytest.raises(gp.PrecisionNotReady) as exc:
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, precision="bf16",
            numerics_capture=str(tmp_path),
        )
    assert "gp.fused" in str(exc.value)


def test_bf16_accepted_with_armed_capture(tmp_path, setup):
    """The full ladder flow: arm the observatory, run the fused f64
    workload so the gp.fused_* sites accumulate evidence, write the
    capture, then present it — bf16 is accepted and agrees with the
    f64 fused result within the covariance-family tolerance."""
    batch, recipe, res = setup
    ll64 = np.asarray(
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, backend="xla"
        )
    )
    numerics.reset()
    numerics.arm()
    try:
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, backend="xla"
        )
        numerics.write(str(tmp_path))
    finally:
        numerics.disarm()
        numerics.reset()
    verdict = numerics.ladder_verdict(
        json.loads((tmp_path / "numerics.json").read_text())
    )
    for site in gp.FUSED_PRECISION_SITES:
        assert verdict[site]["ready"], (site, verdict[site])
    ll16 = np.asarray(
        infer.grid_loglikelihood(
            res, batch, recipe, _GRID, fused=True, precision="bf16",
            backend="xla", numerics_capture=str(tmp_path),
        )
    )
    rel = np.max(np.abs(ll16 - ll64) / np.abs(ll64))
    assert rel < 1e-3, rel


def test_tuner_cache_hit_miss_and_corruption(tmp_path, setup):
    """Rung 3's degradation ladder: a tuned entry is looked up by
    fingerprint; a missing file, a wrong-schema file, and outright
    garbage all silently fall back to the committed default tile."""
    batch, _, _ = setup
    npsr, ntoa = batch.mask.shape
    path = str(tmp_path / "cache.json")
    # miss: no file
    assert tuner.woodbury_tile(batch, "xla", cache_path=path) == \
        pallas_gp.DEFAULT_WOODBURY_TILE
    # hit: a tuned entry under the live fingerprint
    key = tuner.fingerprint("xla", tuner.shape_bucket(npsr, ntoa))
    tuner.save_cache({key: {"tile": 128}}, cache_path=path)
    assert tuner.woodbury_tile(batch, "xla", cache_path=path) == 128
    # a different backend misses the same entry
    assert tuner.woodbury_tile(batch, "pallas", cache_path=path) == \
        pallas_gp.DEFAULT_WOODBURY_TILE
    # wrong schema: behaves like no cache
    (tmp_path / "cache.json").write_text(
        json.dumps({"schema": -1, "entries": {key: {"tile": 128}}})
    )
    assert tuner.woodbury_tile(batch, "xla", cache_path=path) == \
        pallas_gp.DEFAULT_WOODBURY_TILE
    assert tuner.load_cache(path) == {}
    # garbage: behaves like no cache
    (tmp_path / "cache.json").write_text("{not json")
    assert tuner.woodbury_tile(batch, "xla", cache_path=path) == \
        pallas_gp.DEFAULT_WOODBURY_TILE


def test_autotune_writes_cache_the_lookup_reads(tmp_path, setup):
    """The search persists a choice the pure lookup then returns —
    the tuned tile survives the round trip through the file."""
    batch, _, _ = setup
    path = str(tmp_path / "cache.json")
    T = jnp.asarray(
        np.random.default_rng(0).standard_normal(
            (batch.npsr, batch.mask.shape[1], 5)
        )
    )
    choice = tuner.autotune(
        batch, T, backend="xla", candidates=(32, 64), reps=1,
        cache_path=path,
    )
    assert choice["tile"] in (32, 64)
    assert tuner.woodbury_tile(batch, "xla", cache_path=path) == \
        choice["tile"]


def test_build_fused_rejects_noise_cov(setup):
    """The fused build serves the diagonal white/ECORR shape only —
    a recipe with a dense noise covariance is a loud ValueError, not
    a silent wrong answer."""
    from pta_replicator_tpu.covariance.structure import dense_from_times

    batch, recipe, _ = setup
    op = dense_from_times(
        np.asarray(batch.toas_s), np.asarray(batch.mask),
        corr_s=60 * 86400.0, dtype=jnp.float64,
    )
    bad = dataclasses.replace(recipe, noise_cov=op)
    with pytest.raises(ValueError):
        gp.ReducedGP.build_fused(batch, bad)
