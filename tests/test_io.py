import numpy as np
import pytest

from pta_replicator_tpu.io import read_par, read_tim, write_tim
from pta_replicator_tpu.io.tim import fabricate_toas


def test_read_par_small(partim_small):
    pardir, _ = partim_small
    par = read_par(pardir + "/JPSR00.par")
    assert par.name == "JPSR00"
    # RAJ 9:19:49.05 -> hours
    assert par.raj_hours == pytest.approx(9 + 19 / 60 + 49.05 / 3600, rel=1e-12)
    assert par.decj_deg == pytest.approx(-(75 + 42 / 60 + 35.3 / 3600), rel=1e-12)
    assert par.f0 == pytest.approx(205.53069608827312545)
    assert par.f1 == pytest.approx(-4.3060388399134177208e-16)
    assert par.pepoch_mjd == 53000
    assert par.loc == {"RAJ": par.raj_hours, "DECJ": par.decj_deg}


def test_read_tim_small(partim_small):
    _, timdir = partim_small
    toas = read_tim(timdir + "/fake_JPSR00_noiseonly.tim")
    assert toas.ntoas == 122
    assert np.all(toas.errors_s == 0.5e-6)
    assert np.all(toas.freqs_mhz == 1440.0)
    assert toas.observatories[0] == "AXIS"
    assert toas.flags[0] == {"pta": "PPTA"}
    # longdouble precision: fractional day of first TOA preserved to ~ns
    frac = float((toas.mjd[0] - np.longdouble(53000)) * 86400)
    assert abs(frac - 2.33e-05) < 1e-6


def test_tim_roundtrip(tmp_path, partim_small):
    _, timdir = partim_small
    toas = read_tim(timdir + "/fake_JPSR00_noiseonly.tim")
    out = tmp_path / "out.tim"
    write_tim(toas, str(out))
    back = read_tim(str(out))
    assert back.ntoas == toas.ntoas
    # sub-ns epoch round-trip
    assert np.max(np.abs((back.mjd - toas.mjd).astype(float))) * 86400 < 1e-9
    assert np.allclose(back.errors_s, toas.errors_s)


def test_adjust_seconds_precision():
    toas = fabricate_toas(np.linspace(53000, 56000, 100), 0.5)
    before = toas.mjd.copy()
    dt = np.full(100, 1e-6)
    toas.adjust_seconds(dt)
    shift = ((toas.mjd - before) * 86400).astype(float)
    assert np.allclose(shift, 1e-6, rtol=1e-9)


def test_native_parser_matches_python_on_nanograv_tim():
    """The C++ fast path and the Python parser agree field-for-field on a
    real ~7.7k-TOA NANOGrav tim file (long flag tails exercised the
    text-buffer sizing)."""
    path = "/root/reference/test_partim/tim/B1855+09.tim"
    import os

    if not os.path.isfile(path):
        pytest.skip("reference NANOGrav tim not available")
    from pta_replicator_tpu.io.native import fast_read_tim

    if fast_read_tim(path) is None:
        pytest.skip("native toolchain unavailable")
    a = read_tim(path, use_native=True)
    b = read_tim(path, use_native=False)
    assert a.ntoas == b.ntoas == 7758
    np.testing.assert_array_equal(
        np.asarray(a.mjd, float), np.asarray(b.mjd, float)
    )
    np.testing.assert_array_equal(a.get_errors_s(), b.get_errors_s())
    np.testing.assert_array_equal(a.freqs_mhz, b.freqs_mhz)
    np.testing.assert_array_equal(a.get_flag("fe"), b.get_flag("fe"))
    np.testing.assert_array_equal(a.observatories, b.observatories)


def test_tim_roundtrip_randomized(tmp_path):
    """Randomized write->read round trip: longdouble epochs to sub-ns,
    errors, freqs, observatories, and flag tails survive exactly."""
    rng = np.random.default_rng(12)
    for trial in range(5):
        n = int(rng.integers(1, 40))
        toas = fabricate_toas(
            np.sort(53000 + rng.uniform(0, 5000, n)),
            0.1 + rng.uniform(0, 3),
            freq_mhz=float(rng.choice([430.0, 820.0, 1440.0])),
        )
        # per-TOA jittered epochs at sub-us scale + odd flags
        toas.adjust_seconds(rng.uniform(-1e-3, 1e-3, n))
        for j in range(n):
            toas.flags[j] = {
                "fe": f"R{j % 3}", "pta": "NG", "ver": f"v{trial}.{j}",
                "padd": f"{rng.uniform(-1e-6, 1e-6):.3e}",
            }
        p = tmp_path / f"t{trial}.tim"
        write_tim(toas, str(p))
        back = read_tim(str(p))
        assert back.ntoas == n
        assert np.max(np.abs((back.mjd - toas.mjd).astype(float))) * 86400 < 1e-9
        # errors serialize at 10 significant digits (micro-second field)
        np.testing.assert_allclose(back.errors_s, toas.errors_s, rtol=1e-9)
        np.testing.assert_array_equal(back.freqs_mhz, toas.freqs_mhz)
        assert back.flags == toas.flags


def test_par_set_param_precision_roundtrip(tmp_path, partim_small):
    """set_param/write/read preserves F0 at full double precision."""
    from pta_replicator_tpu.io import read_par

    pardir, _ = partim_small
    par = read_par(pardir + "/JPSR00.par")
    new_f0 = 205.530696088273125 + 1.23456789e-13
    par.set_param("F0", new_f0)
    p = tmp_path / "o.par"
    par.write(str(p))
    back = read_par(str(p))
    assert back.f0 == new_f0


def test_fabricate_toas():
    toas = fabricate_toas([53000, 53030], 1.5, freq_mhz=1400.0, flags={"pta": "X"})
    assert toas.ntoas == 2
    assert np.all(toas.errors_s == 1.5e-6)
    assert toas.flags[1] == {"pta": "X"}


def test_write_tim_roundtrip_real_b1855():
    """Native/fallback tim writer round-trips the real NANOGrav B1855+09
    fixture (7.7k TOAs, multi-backend flag tails) bitwise in epoch (the
    parser splits at the decimal point; the writer's fixed 15-decimal
    epochs are exactly representable) and preserves flags/errors."""
    import pathlib

    par = "/root/reference/test_partim/par/B1855+09.par"
    tim = "/root/reference/test_partim/tim/B1855+09.tim"
    if not (pathlib.Path(par).exists() and pathlib.Path(tim).exists()):
        pytest.skip("real B1855 fixture not available")
    from pta_replicator_tpu import load_pulsar
    from pta_replicator_tpu.io.tim import read_tim, write_tim

    psr = load_pulsar(par, tim)
    out = str(pathlib.Path(tim).name) + ".roundtrip"
    import tempfile, os

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, out)
        write_tim(psr.toas, p)
        back = read_tim(p)
        dmjd_s = np.abs((back.mjd - psr.toas.mjd).astype(np.float64)) * 86400.0
        assert dmjd_s.max() < 1e-9
        np.testing.assert_allclose(back.errors_s, psr.toas.errors_s, rtol=1e-9)
        np.testing.assert_allclose(back.freqs_mhz, psr.toas.freqs_mhz, rtol=1e-12)
        assert back.flags[0] == psr.toas.flags[0]
        assert back.flags[-1] == psr.toas.flags[-1]
        assert back.observatories == psr.toas.observatories

        # epoch-only rewrite through an actual cache HIT: populate the
        # static-parts cache, shift the epochs, write again with the
        # cache, and compare against a cache-off write of the same state
        write_tim(psr.toas, os.path.join(d, "warm.tim"),
                  reuse_static_parts=True)
        psr.toas.adjust_seconds(np.full(psr.toas.ntoas, 1.7e-6))
        p2, p3 = os.path.join(d, "c_on.tim"), os.path.join(d, "c_off.tim")
        write_tim(psr.toas, p2, reuse_static_parts=True)
        write_tim(psr.toas, p3)
        assert open(p2, "rb").read() == open(p3, "rb").read()
        assert open(p2, "rb").read() != open(
            os.path.join(d, "warm.tim"), "rb").read()  # epochs did change


def test_write_tim_rejects_control_characters(tmp_path):
    """Metadata containing \\n, \\r, or \\x1f must fail loudly before any
    byte is written — '\\n' forges records in the Python fallback, '\\x1f'
    is the native writer's field separator (would truncate mid-file)."""
    toas = fabricate_toas(np.array([53000.0, 53001.0]), error_us=0.5)
    toas.flags[0]["be"] = "GUP\nPI"
    out = tmp_path / "bad.tim"
    with pytest.raises(ValueError, match="control character"):
        write_tim(toas, str(out))
    assert not out.exists()

    toas.flags[0]["be"] = "GUP\x1fPI"
    with pytest.raises(ValueError, match="control character"):
        write_tim(toas, str(out))
    assert not out.exists()


def test_native_write_error_names_failure(tmp_path):
    """The native writer distinguishes open failures from mid-write
    failures (ERR_WRITE=-4) so the surfaced OSError names the cause."""
    from pta_replicator_tpu.io import native

    if native.load_library() is None:
        pytest.skip("native toolchain unavailable")
    assert native.ERR_WRITE == -4
    day = np.array([53000], dtype=np.int64)
    f15 = np.array([0], dtype=np.int64)
    with pytest.raises(OSError, match="could not open"):
        native.fast_write_tim(str(tmp_path), day, f15, b" a 1\x1fb\n")
