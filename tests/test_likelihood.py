"""likelihood/gp.py + likelihood/infer.py: the rank-reduced GP
likelihood against its dense-covariance oracle, the ReducedGP serving
fast path against the direct evaluation, grid/bank drivers, and the
MAP+Fisher fit. Fixture-free (synthetic batches), f64 (conftest
enables x64)."""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe, realize
from pta_replicator_tpu import likelihood as lk
from pta_replicator_tpu.likelihood import gp


def _full_recipe(batch, seed=0):
    """EFAC/EQUAD/ECORR/red-noise/GWB all active, per-backend tables
    and per-pulsar vectors — the acceptance configuration."""
    nb = len(batch.backend_names)
    rng = np.random.default_rng(seed)
    return Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.4, (batch.npsr, nb))),
        log10_equad=jnp.asarray(
            rng.uniform(-6.8, -6.2, (batch.npsr, nb))
        ),
        log10_ecorr=jnp.asarray(
            rng.uniform(-6.9, -6.4, (batch.npsr, nb))
        ),
        rn_log10_amplitude=jnp.asarray(
            rng.uniform(-13.8, -13.2, batch.npsr)
        ),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, batch.npsr)),
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        rn_nmodes=20,
        gwb_gls_nmodes=15,
    )


def _design(batch, kpad=1):
    """Quadratic-spindown-proxy design tensor with ``kpad`` all-zero
    padding columns (the device path must neutralize them)."""
    t = np.asarray(batch.toas_s)
    scale = np.asarray(batch.tspan_s)[:, None]
    cols = [np.ones_like(t), t / scale, (t / scale) ** 2]
    cols += [np.zeros_like(t)] * kpad
    return np.stack(cols, axis=-1)


def _masked_batch(batch, frac=0.15, seed=9):
    """Knock out a random subset of TOAs (padding-style) so the mask
    handling is exercised, keeping ntoas consistent."""
    rng = np.random.default_rng(seed)
    mask = np.asarray(batch.mask).copy()
    drop = rng.random(mask.shape) < frac
    mask = mask * (~drop)
    return dataclasses.replace(
        batch,
        mask=jnp.asarray(mask, batch.mask.dtype),
        ntoas=jnp.asarray(mask.sum(axis=-1), batch.ntoas.dtype),
    )


@pytest.fixture(scope="module")
def setup():
    batch = synthetic_batch(
        npsr=12, ntoa=300, nbackend=3, seed=1, dtype=jnp.float64
    )
    recipe = _full_recipe(batch)
    rng = np.random.default_rng(5)
    res = jnp.asarray(
        rng.standard_normal(batch.toas_s.shape) * 1e-6
    ) * batch.mask
    return batch, recipe, res


def test_rank_reduced_matches_dense_oracle(setup):
    """THE acceptance criterion: Woodbury/rank-reduced log L ==
    dense-covariance oracle to <= 1e-8 relative, >= 10 pulsars,
    EFAC/EQUAD/ECORR/red-noise/GWB all active, timing model
    marginalized (with padding columns in the design)."""
    batch, recipe, res = setup
    assert batch.npsr >= 10
    design = _design(batch)
    ll = np.asarray(
        gp.loglikelihood(res, batch, recipe, design=design,
                         per_pulsar=True)
    )
    ref = gp.dense_loglikelihood(res, batch, recipe, design=design,
                                 per_pulsar=True)
    rel = np.abs(ll - ref) / np.abs(ref)
    assert rel.max() < 1e-8, rel
    total = float(gp.loglikelihood(res, batch, recipe, design=design))
    assert abs(total - ref.sum()) / abs(ref.sum()) < 1e-8


def test_rank_reduced_matches_dense_no_design(setup):
    batch, recipe, res = setup
    ll = np.asarray(gp.loglikelihood(res, batch, recipe,
                                     per_pulsar=True))
    ref = gp.dense_loglikelihood(res, batch, recipe, per_pulsar=True)
    np.testing.assert_allclose(ll, ref, rtol=1e-9)


def test_rank_reduced_matches_dense_masked(setup):
    """Padded/masked TOAs contribute NOTHING: the likelihood of a
    masked batch equals the dense oracle restricted to valid TOAs."""
    batch, recipe, res = setup
    mbatch = _masked_batch(batch)
    design = _design(mbatch)
    ll = np.asarray(
        gp.loglikelihood(res, mbatch, recipe, design=design,
                         per_pulsar=True)
    )
    ref = gp.dense_loglikelihood(res, mbatch, recipe, design=design,
                                 per_pulsar=True)
    rel = np.abs(ll - ref) / np.abs(ref)
    assert rel.max() < 1e-8, rel


def test_white_noise_only_matches_dense(setup):
    """No GP block at all: the C0-only branch (no Woodbury)."""
    batch, _recipe, res = setup
    recipe = Recipe(efac=jnp.asarray(1.1), log10_equad=jnp.asarray(-6.5))
    ll = np.asarray(gp.loglikelihood(res, batch, recipe,
                                     per_pulsar=True))
    ref = gp.dense_loglikelihood(res, batch, recipe, per_pulsar=True)
    np.testing.assert_allclose(ll, ref, rtol=1e-10)


def test_loglikelihood_prefers_true_noise_model(setup):
    """Sanity on realized data: residuals drawn FROM the recipe score
    higher under it than under badly wrong noise levels. Uses a
    white+red-noise recipe — the likelihood models exactly what those
    ops inject (the GWB synthesis additionally carries sub-1/T
    oversampling power outside any rank-reduced basis, so it is not a
    clean well-specified case; its weighting calibration is pinned in
    test_batched.py instead)."""
    batch, _recipe, _res = setup
    recipe = Recipe(
        efac=jnp.asarray(1.1),
        log10_equad=jnp.asarray(-6.5),
        rn_log10_amplitude=jnp.asarray(-13.4),
        rn_gamma=jnp.asarray(4.0),
        rn_nmodes=20,
    )
    real = realize(jax.random.PRNGKey(3), batch, recipe, nreal=2)
    r0 = jnp.asarray(np.asarray(real)[0])
    design = _design(batch)  # constant column absorbs residualize's
    ll_true = float(gp.loglikelihood(r0, batch, recipe, design=design))
    for wrong in (
        dataclasses.replace(recipe, efac=jnp.asarray(5.5)),
        dataclasses.replace(recipe, efac=jnp.asarray(0.2)),
        dataclasses.replace(
            recipe, rn_log10_amplitude=jnp.asarray(-12.0)
        ),
    ):
        assert ll_true > float(
            gp.loglikelihood(r0, batch, wrong, design=design)
        )


def test_reduced_gp_matches_direct(setup):
    """The serving fast path (fixed-noise precompute + small Cholesky)
    equals the direct evaluation at several hyperparameter points."""
    batch, recipe, res = setup
    design = _design(batch)
    reduced = gp.ReducedGP.build(batch, recipe, design=design)
    proj = reduced.project(res, batch)
    for amp, gamma in [(-14.5, 4.33), (-14.0, 3.5), (-13.8, 5.0)]:
        r2 = dataclasses.replace(
            recipe,
            gwb_log10_amplitude=jnp.asarray(amp),
            gwb_gamma=jnp.asarray(gamma),
        )
        ll_fast = np.asarray(
            reduced.loglikelihood(proj, gp.phi_for_recipe(batch, r2),
                                  per_pulsar=True)
        )
        ll_direct = np.asarray(
            gp.loglikelihood(res, batch, r2, design=design,
                             per_pulsar=True)
        )
        np.testing.assert_allclose(ll_fast, ll_direct, rtol=1e-9)


def test_reduced_gp_rejects_no_basis(setup):
    batch, _recipe, _res = setup
    with pytest.raises(ValueError, match="reduced basis"):
        gp.ReducedGP.build(batch, Recipe(efac=jnp.asarray(1.0)))


def test_grid_matches_pointwise_reduced_and_direct(setup):
    """grid_loglikelihood equals pointwise loglikelihood on BOTH
    routes: a phi-only grid (ReducedGP) and a white-noise grid
    (direct), chunked and unchunked."""
    batch, recipe, res = setup
    grid = {
        "rn_log10_amplitude": np.linspace(-14.2, -13.2, 5),
        "rn_gamma": np.linspace(3.0, 5.0, 5),
    }
    ll = np.asarray(lk.grid_loglikelihood(res, batch, recipe, grid))
    ll_chunked = np.asarray(
        lk.grid_loglikelihood(res, batch, recipe, grid, chunk=2)
    )
    np.testing.assert_allclose(ll, ll_chunked, rtol=0, atol=0)
    for i in [0, 3]:
        r2 = dataclasses.replace(
            recipe,
            rn_log10_amplitude=jnp.asarray(grid["rn_log10_amplitude"][i]),
            rn_gamma=jnp.asarray(grid["rn_gamma"][i]),
        )
        np.testing.assert_allclose(
            ll[i], float(gp.loglikelihood(res, batch, r2)), rtol=1e-9
        )
    # white-noise axis: must route to the direct engine and still match
    wgrid = {"efac": np.asarray([0.8, 1.0, 1.3])}
    assert not lk.infer._reducible(("efac",), recipe)
    wll = np.asarray(lk.grid_loglikelihood(res, batch, recipe, wgrid))
    r2 = dataclasses.replace(recipe, efac=jnp.asarray(1.3))
    np.testing.assert_allclose(
        wll[2], float(gp.loglikelihood(res, batch, r2)), rtol=1e-9
    )


def test_grid_cartesian():
    grid, shape = lk.grid_cartesian(
        {"a": np.arange(3), "b": np.arange(4)}
    )
    assert shape == (3, 4)
    assert grid["a"].shape == (12,)
    assert grid["b"][:4].tolist() == [0, 1, 2, 3]


def test_grid_rejects_static_and_unknown_axes(setup):
    batch, recipe, res = setup
    with pytest.raises(ValueError, match="not a Recipe field"):
        lk.grid_loglikelihood(res, batch, recipe, {"nope": [1.0]})
    with pytest.raises(ValueError, match="static"):
        lk.grid_loglikelihood(res, batch, recipe, {"rn_nmodes": [10]})
    with pytest.raises(ValueError, match="aligned"):
        lk.grid_loglikelihood(
            res, batch, recipe,
            {"rn_gamma": [1.0, 2.0], "rn_log10_amplitude": [1.0]},
        )


def test_bank_loglikelihood_grid_and_mesh(setup):
    """(G, R) bank pricing; identical with the projections sharded
    over the 8-virtual-device mesh's 'real' axis."""
    from pta_replicator_tpu.parallel.mesh import make_mesh

    batch, recipe, _res = setup
    bank = np.asarray(
        realize(jax.random.PRNGKey(1), batch, recipe, nreal=8)
    )
    grid = {"gwb_log10_amplitude": np.linspace(-14.6, -13.9, 4)}
    ll = np.asarray(lk.bank_loglikelihood(bank, batch, recipe,
                                          grid=grid))
    assert ll.shape == (4, 8)
    mesh = make_mesh(8, 1)
    ll_mesh = np.asarray(
        lk.bank_loglikelihood(bank, batch, recipe, grid=grid, mesh=mesh)
    )
    np.testing.assert_allclose(ll, ll_mesh, rtol=1e-12)
    # no grid: per-realization totals at the base recipe
    flat = np.asarray(lk.bank_loglikelihood(bank, batch, recipe))
    assert flat.shape == (8,)
    np.testing.assert_allclose(
        flat[0],
        float(gp.loglikelihood(jnp.asarray(bank[0]), batch, recipe)),
        rtol=1e-9,
    )


def test_bank_grid_rejects_white_noise_axes(setup):
    batch, recipe, _res = setup
    bank = np.zeros((2, batch.npsr, batch.ntoa_max))
    with pytest.raises(ValueError, match="phi-only"):
        lk.bank_loglikelihood(bank, batch, recipe,
                              grid={"efac": [1.0, 1.1]})


def test_map_fit_climbs_and_prices_curvature(setup):
    """Damped Newton: converges, improves on the start, beats (or
    ties) the truth point, and reports finite Fisher sigmas."""
    batch, recipe, _res = setup
    real = realize(jax.random.PRNGKey(11), batch, recipe, nreal=1)
    r0 = jnp.asarray(np.asarray(real)[0])
    start = {"gwb_log10_amplitude": -14.6, "gwb_gamma": 3.8}
    mr = lk.map_fit(r0, batch, recipe, start)
    assert mr.converged
    assert mr.iterations <= 50
    ll_start = float(gp.loglikelihood(
        r0, batch, dataclasses.replace(
            recipe,
            gwb_log10_amplitude=jnp.asarray(-14.6),
            gwb_gamma=jnp.asarray(3.8),
        )
    ))
    ll_truth = float(gp.loglikelihood(r0, batch, recipe))
    assert mr.loglikelihood >= ll_start
    assert mr.loglikelihood >= ll_truth - 1e-6  # the MAP is a maximum
    assert np.all(np.isfinite(mr.sigma))
    d = mr.as_dict()
    assert d["names"] == ["gwb_gamma", "gwb_log10_amplitude"]
    assert np.isfinite(d["loglikelihood"])


def test_loglikelihood_vmaps_over_residuals_and_hypers(setup):
    """The engine contract: jit + vmap over residual banks AND over
    traced Recipe leaves."""
    batch, recipe, _res = setup
    bank = jnp.asarray(np.asarray(
        realize(jax.random.PRNGKey(2), batch, recipe, nreal=3)
    ))

    @jax.jit
    def over_bank(b):
        return jax.vmap(lambda r: gp.loglikelihood(r, batch, recipe))(b)

    out = np.asarray(over_bank(bank))
    assert out.shape == (3,)

    @jax.jit
    def over_amp(amps):
        def one(a):
            r2 = dataclasses.replace(
                recipe, gwb_log10_amplitude=a
            )
            return gp.loglikelihood(bank[0], batch, r2)

        return jax.vmap(one)(amps)

    amps = jnp.asarray([-14.5, -14.0])
    out2 = np.asarray(over_amp(amps))
    r2 = dataclasses.replace(recipe,
                             gwb_log10_amplitude=jnp.asarray(-14.0))
    np.testing.assert_allclose(
        out2[1], float(gp.loglikelihood(bank[0], batch, r2)), rtol=1e-9
    )
