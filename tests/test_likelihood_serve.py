"""likelihood/serve.py: realization banks from sweep checkpoints (all
on-disk states), the request-batched server (coalescing, drain
semantics, SLO stats, telemetry names), the CLI subcommand, and the
bench-diff direction contract for the LIKELIHOOD series."""
import dataclasses
import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe, realize
from pta_replicator_tpu import likelihood as lk
from pta_replicator_tpu.likelihood import gp


@pytest.fixture(scope="module")
def setup():
    batch = synthetic_batch(
        npsr=6, ntoa=128, nbackend=2, seed=2, dtype=jnp.float64
    )
    recipe = Recipe(
        efac=jnp.asarray(1.1),
        log10_equad=jnp.asarray(-6.6),
        log10_ecorr=jnp.asarray(-6.8),
        rn_log10_amplitude=jnp.asarray(-13.5),
        rn_gamma=jnp.asarray(4.0),
        rn_nmodes=10,
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        gwb_gls_nmodes=8,
    )
    bank = np.asarray(
        realize(jax.random.PRNGKey(0), batch, recipe, nreal=16)
    )
    return batch, recipe, bank


# ------------------------------------------------------------- banks

def test_bank_from_consolidated_checkpoint(tmp_path, setup):
    from pta_replicator_tpu.utils.sweep import sweep

    batch, recipe, _bank = setup
    ckpt = str(tmp_path / "sweep.npz")
    ref = sweep(
        jax.random.PRNGKey(4), batch, recipe, nreal=8, chunk=4,
        checkpoint_path=ckpt, reduce_fn=None,
    )
    bank = lk.RealizationBank.from_checkpoint(ckpt)
    assert bank.nreal == 8
    np.testing.assert_array_equal(bank.load(), ref)
    # chunk-at-a-time iteration covers the same bytes
    np.testing.assert_array_equal(
        np.concatenate(list(bank.iter_chunks())), ref
    )


def test_bank_from_inflight_chunk_files(tmp_path, setup):
    """An unfinished sweep's per-chunk .npy files serve as a bank too
    (the serving path does not wait for consolidation)."""
    batch, _recipe, bank_arr = setup
    ckpt = str(tmp_path / "sweep.npz")
    for i in range(3):
        np.save(f"{ckpt}.chunk{i:06d}.npy", bank_arr[i * 4:(i + 1) * 4])
    bank = lk.RealizationBank.from_checkpoint(ckpt)
    assert bank.nreal == 12
    np.testing.assert_array_equal(bank.load(), bank_arr[:12])


def test_bank_refuses_missing_and_reduced(tmp_path, setup):
    batch, _recipe, _bank = setup
    with pytest.raises(FileNotFoundError):
        lk.RealizationBank.from_checkpoint(str(tmp_path / "nope.npz"))
    with pytest.raises(ValueError, match="reduce_fn"):
        lk.RealizationBank.from_array(np.zeros((4, 6)))


def test_iter_checkpoint_chunks_public_helper(tmp_path, setup):
    from pta_replicator_tpu.utils.sweep import (
        iter_checkpoint_chunks,
        load_checkpoint_chunk,
        sweep,
    )

    batch, recipe, _bank = setup
    ckpt = str(tmp_path / "s.npz")
    ref = sweep(
        jax.random.PRNGKey(5), batch, recipe, nreal=8, chunk=4,
        checkpoint_path=ckpt, reduce_fn=None,
    )
    got = dict(iter_checkpoint_chunks(ckpt))
    assert sorted(got) == [0, 1]
    np.testing.assert_array_equal(
        np.concatenate([got[0], got[1]]), ref
    )
    np.testing.assert_array_equal(load_checkpoint_chunk(ckpt, 1), got[1])
    with pytest.raises(FileNotFoundError):
        load_checkpoint_chunk(ckpt, 7)
    # header-only probe agrees with the loaded chunks, consolidated
    # AND in-flight layouts
    from pta_replicator_tpu.utils.sweep import iter_checkpoint_chunk_infos

    infos = list(iter_checkpoint_chunk_infos(ckpt))
    assert [(i, s) for i, s, _d in infos] == [
        (i, got[i].shape) for i in (0, 1)
    ]
    assert all(d == got[i].dtype for i, _s, d in infos)
    ckpt2 = str(tmp_path / "inflight.npz")
    np.save(f"{ckpt2}.chunk000000.npy", got[0])
    infos2 = list(iter_checkpoint_chunk_infos(ckpt2))
    assert infos2 == [(0, got[0].shape, got[0].dtype)]


def test_bank_handle_streams_and_rows(setup):
    """bank_loglikelihood accepts the RealizationBank handle directly
    (projections stream through the prefetch layer — no full-cube
    materialization) and agrees with the array path; row() loads a
    single realization from its containing chunk only."""
    batch, recipe, bank_arr = setup
    bank = lk.RealizationBank.from_array(bank_arr, chunk=4)
    grid = {"gwb_log10_amplitude": np.linspace(-14.6, -13.9, 3)}
    ll_handle = np.asarray(
        lk.bank_loglikelihood(bank, batch, recipe, grid=grid)
    )
    ll_array = np.asarray(
        lk.bank_loglikelihood(bank_arr, batch, recipe, grid=grid)
    )
    np.testing.assert_allclose(ll_handle, ll_array, rtol=1e-12)
    for i in (0, 5, 15):
        np.testing.assert_array_equal(bank.row(i), bank_arr[i])
    with pytest.raises(IndexError):
        bank.row(16)
    with pytest.raises(IndexError):
        bank.row(-1)


# ------------------------------------------------------------- server

def test_server_results_match_direct_path(setup):
    batch, recipe, bank_arr = setup
    bank = lk.RealizationBank.from_array(bank_arr, chunk=8)
    server = lk.LikelihoodServer(
        bank, batch, recipe,
        axes=("gwb_log10_amplitude", "gwb_gamma"),
        max_batch=4, max_delay_s=0.01,
    )
    with server:
        futs = [
            server.submit(gwb_log10_amplitude=-14.2 - 0.05 * i,
                          gwb_gamma=4.0 + 0.1 * i)
            for i in range(7)
        ]
        outs = [f.result(timeout=60) for f in futs]
    for i in (0, 3, 6):
        r2 = dataclasses.replace(
            recipe,
            gwb_log10_amplitude=jnp.asarray(-14.2 - 0.05 * i),
            gwb_gamma=jnp.asarray(4.0 + 0.1 * i),
        )
        direct = np.asarray(jax.vmap(
            lambda r: gp.loglikelihood(r, batch, r2)
        )(jnp.asarray(bank_arr)))
        np.testing.assert_allclose(outs[i], direct, rtol=1e-9)
    stats = server.stats()
    assert stats["requests"] == 7
    assert stats["batches"] >= 2  # 7 requests through capacity-4 batches
    assert 0 < stats["coalesce_efficiency"] <= 1.0
    assert stats["latency"]["count"] == 7
    assert set(stats["latency"]) >= {"p50", "p95", "p99"}
    assert stats["evals"] == 7 * 16


def test_server_coalesces_concurrent_clients(setup):
    """Concurrent submissions coalesce: far fewer batches than
    requests (the deadline/size trigger doing its job)."""
    batch, recipe, bank_arr = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank_arr), batch, recipe,
        axes=("gwb_log10_amplitude",),
        max_batch=8, max_delay_s=0.05,
    )
    results = [None] * 24

    def client(k):
        results[k] = server.submit(
            gwb_log10_amplitude=-14.0 - 0.01 * k
        ).result(timeout=60)

    with server:
        server.evaluate(gwb_log10_amplitude=-14.2)  # compile warmup
        server.reset_stats()
        threads = [
            threading.Thread(target=client, args=(k,)) for k in range(24)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = server.stats()
    assert all(r is not None for r in results)
    assert stats["requests"] == 24
    assert stats["batches"] < 24  # actually coalesced
    assert stats["batch_fill_mean"] > 1.0


def test_server_drains_queue_on_stop(setup):
    """stop() serves queued requests instead of stranding futures."""
    batch, recipe, bank_arr = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank_arr), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=4, max_delay_s=10.0,
    )
    server.start()
    futs = [
        server.submit(rn_log10_amplitude=-13.5 + 0.01 * i)
        for i in range(6)
    ]
    server.stop()
    for f in futs:
        assert np.isfinite(f.result(timeout=5)).all()


def test_server_validates_axes_and_requests(setup):
    batch, recipe, bank_arr = setup
    bank = lk.RealizationBank.from_array(bank_arr)
    with pytest.raises(ValueError, match="phi-only"):
        lk.LikelihoodServer(bank, batch, recipe, axes=("efac",))
    with pytest.raises(ValueError, match="max_batch"):
        lk.LikelihoodServer(bank, batch, recipe,
                            axes=("rn_gamma",), max_batch=0)
    server = lk.LikelihoodServer(bank, batch, recipe,
                                 axes=("rn_gamma",))
    with pytest.raises(RuntimeError, match="not started"):
        server.submit(rn_gamma=4.0)
    with server:
        with pytest.raises(ValueError, match="exactly"):
            server.submit(rn_log10_amplitude=-13.0)


def test_server_emits_registered_telemetry(setup):
    """The SLO metrics land in the registry under their names.py
    constants (the coverage rows in rules_telemetry pin the producer
    side)."""
    from pta_replicator_tpu import obs
    from pta_replicator_tpu.obs import names

    obs.reset_all()
    batch, recipe, bank_arr = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank_arr), batch, recipe,
        axes=("gwb_gamma",), max_batch=2, max_delay_s=0.005,
    )
    with server:
        for _ in range(3):
            server.evaluate(gwb_gamma=4.33)
    snap = obs.REGISTRY.to_json()
    assert snap[names.LIKELIHOOD_REQUESTS][0]["value"] == 3
    assert snap[names.LIKELIHOOD_BATCHES][0]["value"] >= 1
    assert snap[names.LIKELIHOOD_EVALS][0]["value"] == 3 * 16
    assert 0 < snap[names.LIKELIHOOD_COALESCE_EFFICIENCY][0]["value"] <= 1
    # spans: the serve phase span and at least one batch span
    paths = {e["name"] for e in obs.TRACER.events()}
    assert names.SPAN_LIKELIHOOD_SERVE in paths
    assert names.SPAN_LIKELIHOOD_BATCH in paths
    assert names.SPAN_LIKELIHOOD_PROJECT in paths
    obs.reset_all()


def test_project_bank_streams_through_prefetch(setup):
    """project_bank == per-row projection, chunked through the
    prefetch layer: bitwise identical ACROSS depths (the window is
    scheduling, not math), and equal to the full-width vmap at float
    tolerance (XLA fuses the ECORR scatter differently per vmap
    width — a 1-ulp reduction-order effect, same caveat as
    cross-topology sweep resume)."""
    batch, recipe, bank_arr = setup
    reduced = gp.ReducedGP.build(batch, recipe)
    ref = jax.vmap(lambda r: reduced.project(r, batch))(
        jnp.asarray(bank_arr)
    )
    projs = [
        lk.project_bank(
            lk.RealizationBank.from_array(bank_arr, chunk=4),
            reduced, batch, prefetch_depth=depth,
        )
        for depth in (1, 2, 3)
    ]
    for proj in projs:
        np.testing.assert_array_equal(
            np.asarray(proj.rNr), np.asarray(projs[0].rNr)
        )
        np.testing.assert_array_equal(
            np.asarray(proj.d), np.asarray(projs[0].d)
        )
        np.testing.assert_allclose(
            np.asarray(proj.rNr), np.asarray(ref.rNr), rtol=1e-13
        )
        ref_d = np.asarray(ref.d)
        np.testing.assert_allclose(
            np.asarray(proj.d), ref_d, rtol=1e-12,
            atol=1e-12 * np.abs(ref_d).max(),
        )


# ---------------------------------------------------------------- CLI

def test_cli_likelihood_grid_map_and_serve(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    batch = synthetic_batch(npsr=4, ntoa=96, seed=7)
    recipe = Recipe(
        efac=jnp.asarray(1.1),
        rn_log10_amplitude=jnp.asarray(-13.5),
        rn_gamma=jnp.asarray(4.0),
        rn_nmodes=8,
    )
    bank_arr = np.asarray(
        realize(jax.random.PRNGKey(0), batch, recipe, nreal=6)
    )
    bank_path = tmp_path / "bank.npy"
    np.save(bank_path, bank_arr)
    recipe_path = tmp_path / "recipe.json"
    recipe_path.write_text(json.dumps({
        "efac": 1.1, "rn_log10_amplitude": -13.5, "rn_gamma": 4.0,
        "rn_nmodes": 8, "orf": "none",
    }))
    out = tmp_path / "result.json"
    main([
        "likelihood", "--bank", str(bank_path),
        "--recipe", str(recipe_path),
        "--synthetic", "4x96", "--synthetic-seed", "7",
        "--grid", "rn_log10_amplitude=-14.0:-13.0:5",
        "--map", "rn_log10_amplitude=-13.8",
        "--out", str(out),
    ])
    doc = json.loads(out.read_text())
    assert doc["nreal"] == 6
    assert doc["grid"]["shape"] == [5]
    assert len(doc["grid"]["loglikelihood_mean"]) == 5
    assert "rn_log10_amplitude" in doc["grid"]["best"]
    assert doc["map"]["names"] == ["rn_log10_amplitude"]
    # serving demo prints SLO stats
    main([
        "likelihood", "--bank", str(bank_path),
        "--recipe", str(recipe_path),
        "--synthetic", "4x96", "--synthetic-seed", "7",
        "--grid", "rn_log10_amplitude=-14.0:-13.0:5",
        "--serve", "12", "--clients", "3", "--max-batch", "4",
    ])
    doc = json.loads(capsys.readouterr().out.strip())
    assert doc["serve"]["requests"] == 12
    assert "failures" not in doc["serve"]
    assert doc["serve"]["latency"]["count"] == 12
    # --grid and --serve coexist: the scan the user asked for is not
    # silently dropped by the serving demo
    assert doc["grid"]["shape"] == [5]


def test_cli_likelihood_rejects_shape_mismatch(tmp_path):
    from pta_replicator_tpu.__main__ import main

    np.save(tmp_path / "bank.npy", np.zeros((2, 3, 50)))
    recipe_path = tmp_path / "recipe.json"
    recipe_path.write_text(json.dumps({"efac": 1.0, "orf": "none"}))
    with pytest.raises(SystemExit, match="different dataset"):
        main([
            "likelihood", "--bank", str(tmp_path / "bank.npy"),
            "--recipe", str(recipe_path), "--synthetic", "4x96",
        ])


# ------------------------------------------------- bench-diff contract

def test_likelihood_bench_diff_directions():
    """The LIKELIHOOD series' leaves classify the way the gate
    promises: evals_per_s / coalesce_efficiency higher-better, latency
    percentiles lower-better — and the committed round JSON diffs
    cleanly against itself (exit 0, nothing regressed)."""
    import os

    from pta_replicator_tpu.obs.regress import bench_diff, metric_direction

    assert metric_direction("raw_eval.evals_per_s") is True
    assert metric_direction("serve.evals_per_s") is True
    assert metric_direction("serve.coalesce_efficiency") is True
    assert metric_direction("serve.requests_per_s") is True
    assert metric_direction("serve.latency.p50") is False
    assert metric_direction("serve.latency.p95") is False
    assert metric_direction("serve.latency.p99") is False
    assert metric_direction("raw_eval.reduced_speedup") is True

    path = os.path.join(os.path.dirname(__file__), "..",
                        "LIKELIHOOD_r09_cpu.json")
    assert os.path.exists(path), (
        "LIKELIHOOD_r09_cpu.json must be committed with the likelihood "
        "bench evidence"
    )
    _table, summary, rc = bench_diff([path, path])
    assert rc == 0 and summary["regressed"] == 0
    assert summary["comparable"] > 10

# ------------------------------- PR 11: admission control + deadlines

def _blocked_engine_server(setup, **kw):
    """A started server whose engine is swapped for a gate: the first
    batch enters and blocks until released, so the queue backs up
    deterministically (no timing races)."""
    import threading as _threading

    batch, recipe, bank_arr = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank_arr), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=1, max_delay_s=0.001,
        **kw,
    )
    entered = _threading.Event()
    release = _threading.Event()
    nreal = bank_arr.shape[0]

    def gated_engine(theta, *a, **k):
        entered.set()
        release.wait(30.0)
        return np.zeros((theta.shape[0], nreal))

    server._engine = gated_engine
    return server, entered, release


def test_server_rejects_on_saturation(setup):
    """max_queue admission control: submissions past the bound raise
    ServerSaturated WITHOUT enqueueing; the SLO counters advance; the
    admitted requests are all served after release."""
    from pta_replicator_tpu.obs import counter, names

    server, entered, release = _blocked_engine_server(setup, max_queue=2)
    r0 = counter(names.LIKELIHOOD_REJECTED).value
    with server:
        first = server.submit(rn_log10_amplitude=-13.5)
        assert entered.wait(10.0)  # worker holds it inside the engine
        queued = [server.submit(rn_log10_amplitude=-13.5 - 0.1 * k)
                  for k in range(2)]
        with pytest.raises(lk.ServerSaturated, match="max_queue=2"):
            server.submit(rn_log10_amplitude=-14.9)
        stats = server.stats()
        assert stats["rejected"] == 1
        assert counter(names.LIKELIHOOD_REJECTED).value == r0 + 1
        release.set()
    for f in [first] + queued:
        assert f.done() and f.exception() is None
    # the rejected request was never admitted: requests == served
    assert server.stats()["requests"] == 3


def test_server_deadline_expiry_under_saturation(setup):
    """Requests stuck in a saturated queue past their deadline have
    their futures RAISE DeadlineExpired (never strand, never evaluate
    late); counters advance; stop() leaves no pending future."""
    from pta_replicator_tpu.obs import counter, names

    server, entered, release = _blocked_engine_server(
        setup, request_deadline_s=0.05
    )
    d0 = counter(names.LIKELIHOOD_DEADLINE_EXPIRED).value
    with server:
        first = server.submit(rn_log10_amplitude=-13.5)
        assert entered.wait(10.0)
        stale = [server.submit(rn_log10_amplitude=-13.6 - 0.1 * k)
                 for k in range(3)]
        # a per-submit override beats the server default
        fresh = server.submit(deadline_s=60.0, rn_log10_amplitude=-14.0)
        time.sleep(0.15)  # all default-deadline requests expire queued
        release.set()
    assert first.done() and first.exception() is None
    assert fresh.done() and fresh.exception() is None
    for f in stale:
        assert f.done()
        with pytest.raises(lk.DeadlineExpired, match="expired after"):
            f.result(timeout=0)
    stats = server.stats()
    assert stats["deadline_expired"] == 3
    assert counter(names.LIKELIHOOD_DEADLINE_EXPIRED).value == d0 + 3
    # expired requests never reached the engine: of 5 submitted, only
    # the blocked first + the fresh override were SERVED
    assert stats["requests"] == 2


def test_server_stop_expires_rather_than_strands(setup):
    """The stop() drain applies deadlines too: an expired queued
    request raises instead of being served late or stranded."""
    server, entered, release = _blocked_engine_server(
        setup, request_deadline_s=0.05
    )
    server.start()
    first = server.submit(rn_log10_amplitude=-13.5)
    assert entered.wait(10.0)
    stale = server.submit(rn_log10_amplitude=-13.7)
    time.sleep(0.15)
    release.set()
    server.stop()
    assert first.done() and stale.done()
    assert isinstance(stale.exception(), lk.DeadlineExpired)
