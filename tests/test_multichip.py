"""Multi-chip sweep path: per-shard readback, per-device prefetch,
sharded checkpoints (bit-identity, crash injection, mesh-shape-change
resume), and the fast 8-host-device smoke that guards mesh regressions
on CPU before a TPU tunnel window is spent.

Runs on the 8 virtual CPU devices conftest.py forces for every test
session. The strict bit-identity tests use recipes/chunk sizes in the
regime where XLA's shape-dependent lowering is provably stable (>= 2
realizations per shard; see test_mesh_sweep_bit_identity) — the
documented caveat in utils.sweep covers the rest (cross-topology float
reduction order), asserted here at f64 tightness.
"""
import glob
import os
import zipfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import importlib

sweep_mod = importlib.import_module("pta_replicator_tpu.utils.sweep")
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.parallel.mesh import (
    fetch_shard_blocks,
    make_mesh,
    put_sharded,
)
from pta_replicator_tpu.parallel.pipeline import DrainTimeout
from pta_replicator_tpu.parallel.prefetch import prefetch_to_mesh
from pta_replicator_tpu.utils.sweep import (
    ShardedBlock,
    load_shard_archive,
    sweep,
    write_shard_archive,
)


@pytest.fixture()
def white_sweep():
    """Elementwise-only recipe (no contraction for XLA to re-tile):
    bit-identical across every topology at >= 2 realizations/shard."""
    b = synthetic_batch(npsr=4, ntoa=64, nbackend=2, seed=2)
    recipe = Recipe(
        efac=jnp.full((4, 2), 1.1),
        log10_equad=jnp.full((4, 2), -6.5),
    )
    return b, recipe, jax.random.PRNGKey(5)


@pytest.fixture()
def rn_sweep():
    b = synthetic_batch(npsr=4, ntoa=64, seed=2)
    recipe = Recipe(
        efac=jnp.ones(4),
        rn_log10_amplitude=jnp.full(4, -14.0),
        rn_gamma=jnp.full(4, 4.0),
    )
    return b, recipe, jax.random.PRNGKey(5)


# ------------------------------------------------ per-shard readback

def test_put_sharded_matches_device_put():
    mesh = make_mesh(4, 2)
    x = np.arange(8 * 6 * 10, dtype=np.float64).reshape(8, 6, 10)
    spec = P("real", "psr", None)
    a = put_sharded(x, mesh, spec)
    b = jax.device_put(x, NamedSharding(mesh, spec))
    assert a.sharding.is_equivalent_to(b.sharding, x.ndim)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # placing an already-placed array is a no-op (same object)
    assert put_sharded(a, mesh, spec) is a


def test_put_sharded_reshards_device_arrays_on_device(monkeypatch):
    """A device-resident input (static_delays' freshly computed plane)
    reshards via device_put — no host round-trip fencing compute."""
    from pta_replicator_tpu.parallel import mesh as mesh_mod

    mesh = make_mesh(4, 2)
    x = np.arange(8 * 6, dtype=np.float64).reshape(8, 6)
    spec = P("real", "psr")
    on_dev = jax.device_put(x, jax.devices()[0])
    want = np.asarray(put_sharded(x, mesh, spec))

    def no_host(*a, **k):
        raise AssertionError("device array took the host round-trip")

    monkeypatch.setattr(mesh_mod.np, "asarray", no_host)
    out = put_sharded(on_dev, mesh, spec)
    monkeypatch.undo()
    assert out.sharding.is_equivalent_to(
        NamedSharding(mesh, spec), x.ndim)
    np.testing.assert_array_equal(np.asarray(out), want)


def test_fetch_shard_blocks_assembles_bit_identical():
    mesh = make_mesh(4, 2)
    x = np.arange(8 * 6 * 10, dtype=np.float64).reshape(8, 6, 10)
    arr = put_sharded(x, mesh, P("real", "psr", None))
    blk = fetch_shard_blocks(arr)
    assert isinstance(blk, ShardedBlock)
    assert len(blk.shards) == 8
    assert blk.nbytes == x.nbytes  # disjoint cover, no replication
    np.testing.assert_array_equal(blk.assemble(), np.asarray(arr))


def test_fetch_shard_blocks_dedups_replicated_axis():
    """A result that does not use one mesh axis carries replicated
    shards — fetched once per distinct index window, not per device."""
    mesh = make_mesh(4, 2)
    x = np.arange(8 * 5, dtype=np.float64).reshape(8, 5)
    arr = put_sharded(x, mesh, P("real", None))
    blk = fetch_shard_blocks(arr)
    assert len(blk.shards) == 4
    assert blk.nbytes == x.nbytes
    np.testing.assert_array_equal(blk.assemble(), x)


def test_fetch_shard_blocks_single_device_passthrough():
    x = jnp.arange(12.0)
    out = fetch_shard_blocks(jax.device_put(x, jax.devices()[0]))
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.arange(12.0))


def test_sharded_block_refuses_partial_cover():
    blk = ShardedBlock((4, 2), np.float64,
                       [(((0, 2), (0, 2)), np.zeros((2, 2)))])
    with pytest.raises(ValueError, match="partial"):
        blk.assemble()


# ------------------------------------------------ shard archive format

def test_shard_archive_roundtrip(tmp_path):
    mesh = make_mesh(4, 2)
    x = np.arange(8 * 6 * 10, dtype=np.float64).reshape(8, 6, 10)
    blk = fetch_shard_blocks(put_sharded(x, mesh, P("real", "psr", None)))
    path = str(tmp_path / "chunk.npz")
    write_shard_archive(path, blk)
    np.testing.assert_array_equal(load_shard_archive(path), x)
    # manifest member is LAST (the completeness marker)
    names = zipfile.ZipFile(path).namelist()
    assert names[-1] == "manifest.npy"


def test_shard_archive_refuses_torn_file(tmp_path):
    """An archive without the manifest member (torn mid-write) must be
    refused, never silently half-assembled."""
    path = str(tmp_path / "torn.npz")
    with zipfile.ZipFile(path, "w") as zf:
        with zf.open("shard000000.npy", "w") as fh:
            fh.write(sweep_mod.npy_bytes(np.zeros(3)))
    with pytest.raises(ValueError, match="manifest"):
        load_shard_archive(path)


# ------------------------------------ sharded-checkpoint sweep paths

@pytest.mark.parametrize("shape", [(1, 1), (2, 2), (4, 2)])
def test_mesh_sweep_bit_identity(tmp_path, white_sweep, shape):
    """The ISSUE's core contract at mesh shapes 1x1 / 2x2 / 4x2: the
    mesh sweep with sharded checkpoints returns results AND writes a
    consolidated npz bit-identical to the single-chip pipelined path."""
    b, recipe, key = white_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ref_ck,
                reduce_fn=None, pipeline_depth=2)
    mesh = make_mesh(*shape)
    ck = str(tmp_path / "mesh.npz")
    out = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
                reduce_fn=None, mesh=mesh, pipeline_depth=2)
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()
    assert glob.glob(ck + ".chunk*") == []  # consolidated away


def test_mesh_sweep_rn_recipe_close_and_format_identical(tmp_path, rn_sweep):
    """Red noise adds a partitioned contraction: cross-topology results
    agree to float-reduction-order (documented caveat), while on the
    SAME mesh the sharded-checkpoint format itself changes nothing —
    byte-equal consolidated npz vs shard_checkpoint=False."""
    b, recipe, key = rn_sweep
    mesh = make_mesh(4, 2)
    ck_s = str(tmp_path / "sharded.npz")
    ck_p = str(tmp_path / "plain.npz")
    out_s = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck_s,
                  mesh=mesh, pipeline_depth=2)
    out_p = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck_p,
                  mesh=mesh, pipeline_depth=2, shard_checkpoint=False)
    np.testing.assert_array_equal(out_s, out_p)
    assert open(ck_s, "rb").read() == open(ck_p, "rb").read()
    # cross-topology: f64-tight but not necessarily bitwise
    ref = sweep(key, b, recipe, nreal=16, chunk=4,
                checkpoint_path=str(tmp_path / "ref.npz"))
    np.testing.assert_allclose(out_s, ref, rtol=1e-12, atol=1e-18)


def test_mesh_sweep_writes_sharded_chunk_files(tmp_path, white_sweep):
    """Mid-sweep the chunk files ARE sharded archives (npz members per
    shard + manifest), landed through the atomic layer."""
    b, recipe, key = white_sweep
    mesh = make_mesh(2, 2)

    class Stop(Exception):
        pass

    def bomb(done, total):
        if done == 2:
            raise Stop

    ck = str(tmp_path / "s.npz")
    with pytest.raises(Stop):
        sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
              mesh=mesh, progress=bomb, pipeline_depth=2)
    chunks = sorted(glob.glob(ck + ".chunk*"))
    assert chunks and all(c.endswith(".npz") for c in chunks)
    with zipfile.ZipFile(chunks[0]) as zf:
        names = zf.namelist()
    assert "manifest.npy" in names
    assert sum(n.startswith("shard") for n in names) == 4  # 2x2 mesh


def test_crash_mid_shard_write_resumes(tmp_path, white_sweep, monkeypatch):
    """Kill between a sharded chunk archive landing and its sidecar —
    the crash-safety window — then resume on a DIFFERENT mesh shape and
    still match the uninterrupted single-chip run byte-for-byte."""
    b, recipe, key = white_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ref_ck,
                reduce_fn=None, pipeline_depth=2)

    class _KillSim(BaseException):
        pass

    orig = sweep_mod._atomic_write
    seen = {"json": 0}

    def bombed(write_fn, final_path, suffix, durable=False):
        if suffix == ".json":
            seen["json"] += 1
            if seen["json"] == 3:  # chunk index 2's sidecar
                raise _KillSim()
        return orig(write_fn, final_path, suffix, durable=durable)

    monkeypatch.setattr(sweep_mod, "_atomic_write", bombed)
    ck = str(tmp_path / "crash.npz")
    with pytest.raises(_KillSim):
        sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
              reduce_fn=None, mesh=make_mesh(2, 2), pipeline_depth=2)
    monkeypatch.undo()

    # chunk 2's sharded archive landed, its sidecar did not
    assert os.path.exists(ck + ".chunk000002.npz")
    calls = []
    out = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
                reduce_fn=None, mesh=make_mesh(4, 2), pipeline_depth=2,
                progress=lambda d, t: calls.append(d))
    assert calls == [3, 4]  # chunks 0,1 reloaded from sharded archives
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


@pytest.mark.parametrize("direction", ["mesh_to_single", "single_to_mesh"])
def test_resume_across_topology_change(tmp_path, white_sweep, direction):
    """A sweep checkpointed under one topology resumes under another
    (the preemption case): sharded chunks reassemble via their
    manifests, single-chip chunks load as before, and the result +
    consolidated npz match the uninterrupted reference bitwise."""
    b, recipe, key = white_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ref_ck,
                pipeline_depth=2)

    class Stop(Exception):
        pass

    def bomb(done, total):
        if done == 2:
            raise Stop

    first = make_mesh(2, 2) if direction == "mesh_to_single" else None
    second = None if direction == "mesh_to_single" else make_mesh(4, 2)
    ck = str(tmp_path / "topo.npz")
    with pytest.raises(Stop):
        sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
              mesh=first, progress=bomb)
    calls = []
    out = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
                mesh=second, progress=lambda d, t: calls.append(d))
    assert calls == [3, 4]
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_shard_checkpoint_requires_mesh(tmp_path, white_sweep):
    b, recipe, key = white_sweep
    with pytest.raises(ValueError, match="multi-device mesh"):
        sweep(key, b, recipe, nreal=8, chunk=4,
              checkpoint_path=str(tmp_path / "x.npz"),
              shard_checkpoint=True)


# --------------------------------------------- per-device prefetch

def _tiles(n=5, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.random((3, 16)), rng.random((5, 8, 16)))
            for _ in range(n)]


def test_prefetch_to_mesh_matches_device_put():
    mesh = make_mesh(4, 2)
    tiles = _tiles()
    specs = (P(), P(None, "psr", None))
    got = list(prefetch_to_mesh(iter(tiles), mesh, specs=specs, depth=2))
    assert len(got) == len(tiles)
    for (src, psr), (g_src, g_psr) in zip(tiles, got):
        ref = jax.device_put(psr, NamedSharding(mesh, P(None, "psr", None)))
        np.testing.assert_array_equal(np.asarray(g_src), src)
        np.testing.assert_array_equal(np.asarray(g_psr), np.asarray(ref))
        assert g_psr.sharding.is_equivalent_to(ref.sharding, psr.ndim)


def test_prefetch_to_mesh_error_reraises_in_order():
    """A tile-build failure re-raises UNCHANGED on the consumer, after
    every earlier tile was yielded — no device may abandon a tile its
    peers already staged."""
    mesh = make_mesh(4, 2)
    tiles = _tiles()

    class Boom(Exception):
        pass

    def gen():
        yield tiles[0]
        yield tiles[1]
        raise Boom("tile build failed")

    it = prefetch_to_mesh(gen(), mesh,
                          specs=(P(), P(None, "psr", None)), depth=2)
    got = []
    with pytest.raises(Boom, match="tile build failed"):
        for t in it:
            got.append(t)
    assert len(got) == 2
    for (src, _), (g_src, _) in zip(tiles, got):
        np.testing.assert_array_equal(np.asarray(g_src), src)


def test_prefetch_to_mesh_stall_raises_drain_timeout():
    import threading

    mesh = make_mesh(2, 1)
    hang = threading.Event()

    def gen():
        yield _tiles(1)[0]
        hang.wait(20.0)  # wedged host precompute
        yield _tiles(1)[0]

    it = prefetch_to_mesh(gen(), mesh, specs=(P(), P()), depth=1,
                          stall_timeout_s=0.5)
    next(it)
    with pytest.raises(DrainTimeout):
        next(it)
    hang.set()


def test_prefetch_to_mesh_consumer_abandon_no_hang():
    mesh = make_mesh(2, 1)
    it = prefetch_to_mesh(iter(_tiles(8)), mesh, specs=(P(), P()), depth=2)
    next(it)
    it.close()  # must join workers promptly, not hang


def test_cw_stream_response_mesh_bit_identical():
    """The streamed CW plane build on a mesh (per-device staging,
    psr-sharded accumulator) is bit-identical to the single-device
    stream — per-pulsar accumulation order is unchanged."""
    from pta_replicator_tpu.models.batched import (
        cw_catalog_plane_tiles_for,
        cw_stream_response,
    )

    b = synthetic_batch(npsr=4, ntoa=64, seed=3)
    rng = np.random.default_rng(1)
    ncw = 24
    params = [
        np.arccos(rng.uniform(-1, 1, ncw)),
        rng.uniform(0, 2 * np.pi, ncw),
        10 ** rng.uniform(8, 9.5, ncw),
        rng.uniform(50, 1000, ncw),
        10 ** rng.uniform(-8.8, -7.6, ncw),
        rng.uniform(0, 2 * np.pi, ncw),
        rng.uniform(0, np.pi, ncw),
        np.arccos(rng.uniform(-1, 1, ncw)),
    ]

    def tiles():
        return cw_catalog_plane_tiles_for(b, *params, chunk=8)

    ref = np.asarray(cw_stream_response(b, tiles(), evolve=True))
    for shape in [(2, 2), (4, 1)]:
        mesh = make_mesh(*shape)
        got = cw_stream_response(b, tiles(), evolve=True, mesh=mesh)
        assert len(got.sharding.device_set) == shape[0] * shape[1]
        np.testing.assert_array_equal(np.asarray(got), ref)


# ------------------------------------------------ 8-device CPU smoke

def test_multichip_smoke_8_devices(tmp_path, white_sweep):
    """Fast tier-1 smoke over all 8 virtual CPU devices: a tiny mesh
    sweep down the full multi-chip path (sharded dispatch, per-shard
    readback, sharded checkpoints, multichip_sweep phase span) — mesh
    regressions surface here before a TPU tunnel window is spent."""
    assert jax.device_count() >= 8, "conftest must force 8 host devices"
    b, recipe, key = white_sweep
    from pta_replicator_tpu import obs

    obs.reset_all()
    mesh = make_mesh(4, 2)
    ck = str(tmp_path / "smoke.npz")
    out = sweep(key, b, recipe, nreal=16, chunk=8, checkpoint_path=ck,
                mesh=mesh, pipeline_depth=2)
    assert out.shape == (16, 4)
    assert np.isfinite(out).all()
    # the phase span for occupancy attribution was emitted
    spans = [e for e in obs.TRACER.events()
             if e.get("type") == "span" and e.get("name") == "multichip_sweep"]
    assert len(spans) == 1
    assert spans[0]["attrs"]["mesh"] == "4x2"
    occ = obs.occupancy.analyze(obs.TRACER.events())
    assert occ and "bottleneck" in occ


# ------------------------------------------- bench-diff directions

def test_regress_directions_for_multichip_series():
    from pta_replicator_tpu.obs.regress import metric_direction

    assert metric_direction("scaling_efficiency") is True
    assert metric_direction("arms.8.scaling_efficiency") is True
    assert metric_direction("per_device_real_per_s") is True
    # host properties, not scores: no direction
    assert metric_direction("arms.8.attainable_speedup") is None
    assert metric_direction("arms.8.compute_util_cores") is None


def test_prefetch_to_mesh_mid_stage_exception_peers_drain():
    """A fault injected INSIDE one device's staging call (mid-stage,
    after peers may already have staged their pieces of the same tile)
    re-raises on the consumer after every earlier tile was yielded in
    order — no peer stager hangs, no yielded tile is stranded, and all
    workers join promptly (ISSUE 11 satellite: the multi-device
    abandon path under a mid-stage exception)."""
    import time as _time

    from pta_replicator_tpu.faults import inject
    from pta_replicator_tpu.faults.inject import InjectedFault

    mesh = make_mesh(4, 2)
    tiles = _tiles(8)
    got = []
    t0 = _time.monotonic()
    # fatal => the staging retry must NOT absorb it; call=13 lands the
    # fault mid-tile on one stager after 12 healthy per-device stagings
    # (8 devices x tile 0 + part of tile 1's fan-out)
    with inject.armed("cw_stream_stage:fatal@call=13"):
        it = prefetch_to_mesh(
            iter(tiles), mesh, specs=(P(), P(None, "psr", None)), depth=2
        )
        with pytest.raises(InjectedFault):
            for t in it:
                got.append(t)
    assert _time.monotonic() - t0 < 30.0  # drained, not wedged
    # every tile yielded before the fault is complete and in order (how
    # many made it out is scheduling-dependent: the faulted device may
    # race ahead of a peer still on tile 0 — the contract is the
    # PREFIX, the clean join, and the unchanged re-raise)
    assert len(got) < len(tiles)
    for (src, psr), (g_src, g_psr) in zip(tiles, got):
        np.testing.assert_array_equal(np.asarray(g_src), src)
        np.testing.assert_array_equal(np.asarray(g_psr), psr)


# ------------------------------------------- fused mesh path (r17)

@pytest.mark.parametrize("shape", [(2, 2), (4, 2)])
def test_fused_mesh_sweep_bit_identity(tmp_path, white_sweep, shape):
    """The r17 tentpole contract: ONE fused stage graph running the
    whole multi-chip sweep (host tile build -> per-device H2D ->
    sharded compute -> per-shard D2H -> parallel per-shard writers) is
    byte-identical to both the stacked mesh sweep and the single-chip
    pipelined reference, at >= 2 mesh shapes."""
    b, recipe, key = white_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ref_ck,
                reduce_fn=None, pipeline_depth=2)
    mesh = make_mesh(*shape)
    stacked_ck = str(tmp_path / "stacked.npz")
    stacked = sweep(key, b, recipe, nreal=32, chunk=8,
                    checkpoint_path=stacked_ck, reduce_fn=None,
                    mesh=mesh, pipeline_depth=2)
    fused_ck = str(tmp_path / "fused.npz")
    fused = sweep(key, b, recipe, nreal=32, chunk=8,
                  checkpoint_path=fused_ck, reduce_fn=None,
                  mesh=mesh, pipeline_depth=2, fused_stream=True)
    np.testing.assert_array_equal(fused, ref)
    np.testing.assert_array_equal(fused, stacked)
    ref_bytes = open(ref_ck, "rb").read()
    assert open(fused_ck, "rb").read() == ref_bytes
    assert open(stacked_ck, "rb").read() == ref_bytes
    assert glob.glob(fused_ck + ".chunk*") == []


def test_fused_mesh_crash_resume_across_mesh_change(
    tmp_path, white_sweep, monkeypatch
):
    """Kill a fused mesh sweep in the crash-safety window (sharded
    archive landed, sidecar missing), resume FUSED on a different mesh
    shape, and still match the uninterrupted single-chip run
    byte-for-byte."""
    b, recipe, key = white_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ref_ck,
                reduce_fn=None, pipeline_depth=2)

    class _KillSim(BaseException):
        pass

    orig = sweep_mod._atomic_write
    seen = {"json": 0}

    def bombed(write_fn, final_path, suffix, durable=False):
        if suffix == ".json":
            seen["json"] += 1
            if seen["json"] == 2:  # chunk index 1's sidecar
                raise _KillSim()
        return orig(write_fn, final_path, suffix, durable=durable)

    monkeypatch.setattr(sweep_mod, "_atomic_write", bombed)
    ck = str(tmp_path / "crash.npz")
    with pytest.raises(_KillSim):
        sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
              reduce_fn=None, mesh=make_mesh(2, 2), pipeline_depth=2,
              fused_stream=True, chunk_retries=0)
    monkeypatch.undo()

    assert os.path.exists(ck + ".chunk000001.npz")
    calls = []
    out = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
                reduce_fn=None, mesh=make_mesh(4, 2), pipeline_depth=2,
                fused_stream=True, progress=lambda d, t: calls.append(d))
    assert calls == [2, 3, 4]  # chunk 0 survived; 1..3 recomputed
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


@pytest.mark.parametrize("shape", [(2, 2), (4, 2)])
@pytest.mark.parametrize("schedule", [
    "io_write:raise@chunk=1",
    # call=2 is the SECOND per-shard fire of chunk 0's archive: a torn
    # fault on ONE shard of a multi-shard archive (the in-flight tmp
    # file is truncated mid-parallel-write, peers' bytes included)
    "checkpoint_write:torn@call=2",
])
def test_fused_mesh_chaos_recovers_byte_identical(
    tmp_path, white_sweep, shape, schedule
):
    """io_write / checkpoint_write fault schedules on the FUSED mesh
    path — including torn-on-one-shard — recover byte-identically via
    sidecar resume at both mesh shapes."""
    from pta_replicator_tpu.faults import inject
    from pta_replicator_tpu.faults.retry import RetryPolicy

    b, recipe, key = white_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ref_ck,
                reduce_fn=None, pipeline_depth=2)
    ck = str(tmp_path / "chaos.npz")
    fast = RetryPolicy(max_attempts=4, base_delay_s=0.01, max_delay_s=0.05)
    with inject.armed(schedule):
        out = sweep(key, b, recipe, nreal=32, chunk=8, checkpoint_path=ck,
                    reduce_fn=None, mesh=make_mesh(*shape),
                    pipeline_depth=2, fused_stream=True,
                    retry_policy=fast)
        assert len(inject.fired()) == 1  # the fault really fired
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_shard_archive_byte_stable_across_writer_counts(tmp_path):
    """The parallel per-shard writer is byte-deterministic: any writer
    count (serial included) produces the identical archive — offsets
    are precomputed, the manifest + central directory commit last."""
    mesh = make_mesh(4, 2)
    x = np.arange(8 * 6 * 10, dtype=np.float64).reshape(8, 6, 10)
    blk = fetch_shard_blocks(put_sharded(x, mesh, P("real", "psr", None)))
    paths = []
    for w in (1, 2, 4, None):
        p = str(tmp_path / f"w{w}.npz")
        write_shard_archive(p, blk, writers=w)
        paths.append(p)
        np.testing.assert_array_equal(load_shard_archive(p), x)
    ref = open(paths[0], "rb").read()
    for p in paths[1:]:
        assert open(p, "rb").read() == ref
    # still a valid zip with per-member CRCs (np.load checks them)
    with zipfile.ZipFile(paths[0]) as zf:
        assert zf.testzip() is None
        assert zf.namelist()[-1] == "manifest.npy"


def test_shard_archive_parallel_writer_telemetry(tmp_path):
    """Each shard writer emits a shard_write{shard=} span nested in the
    chunk's io_write shadow, and durable archives count one fsync per
    shard writer."""
    from pta_replicator_tpu import obs
    from pta_replicator_tpu.obs import names as obs_names

    mesh = make_mesh(2, 2)
    x = np.arange(4 * 6, dtype=np.float64).reshape(4, 6)
    blk = fetch_shard_blocks(put_sharded(x, mesh, P("real", "psr")))
    obs.reset_all()
    f0 = obs.counter(obs_names.SWEEP_SHARD_FSYNCS).value
    write_shard_archive(str(tmp_path / "t.npz"), blk, durable=True)
    spans = [e for e in obs.TRACER.events()
             if e.get("type") == "span"
             and e.get("name") == obs_names.SPAN_SHARD_WRITE]
    assert sorted(e["attrs"]["shard"] for e in spans) == [0, 1, 2, 3]
    assert all(e["attrs"]["nbytes"] > 0 for e in spans)
    assert obs.counter(obs_names.SWEEP_SHARD_FSYNCS).value == f0 + 4


def test_prefetch_to_mesh_transient_stage_fault_retried():
    """A transient per-device staging failure is absorbed by the
    in-place retry: the stream completes, bit-identical, with the
    retry visible in telemetry."""
    from pta_replicator_tpu.faults import inject
    from pta_replicator_tpu.obs import counter, names as obs_names

    mesh = make_mesh(2, 1)
    tiles = _tiles(5)
    r0 = counter(obs_names.CW_STREAM_STAGE_RETRIES).value
    with inject.armed("cw_stream_stage:device_lost@call=4"):
        got = list(prefetch_to_mesh(iter(tiles), mesh,
                                    specs=(P(), P()), depth=2))
    assert len(got) == 5
    for (src, _), (g_src, _) in zip(tiles, got):
        np.testing.assert_array_equal(np.asarray(g_src), src)
    assert counter(obs_names.CW_STREAM_STAGE_RETRIES).value == r0 + 1
