"""The numerics observatory (obs/numerics.py, PR 18): identity-on-the-
data-path probes, the donated-stats collector the realize engine drains,
non-finite episodes, shadow-oracle drift, and the precision ledger's
persistence/report surface. The flagship-scale evidence lives in
benchmarks/numerics_probe.py (NUMERICS_r18_cpu.json); these are the
fast behavioral pins.
"""
import hashlib
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe, realize
from pta_replicator_tpu.obs import numerics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _pristine():
    """Every test starts and ends disarmed with an empty ledger; the
    disarm clears jax caches so a probed trace never leaks into the
    next test's (or suite's) disarmed graphs."""
    numerics.disarm()
    numerics.reset()
    yield
    numerics.disarm()
    numerics.reset()


@pytest.fixture()
def small():
    b = synthetic_batch(npsr=3, ntoa=64, seed=3)
    recipe = Recipe(
        efac=jnp.ones(3),
        rn_log10_amplitude=jnp.full(3, -14.0),
        rn_gamma=jnp.full(3, 4.0),
    )
    return b, recipe, jax.random.PRNGKey(11)


def _cube_sha(b, recipe, key, nreal=8):
    out = np.asarray(realize(key, b, recipe, nreal=nreal))
    return hashlib.sha256(out.tobytes()).hexdigest()


# ------------------------------------------------------------ identity

def test_disarmed_probes_are_bitwise_todays_graph(small):
    """The core contract: the realize cube is sha256-identical across
    disarmed / armed / disarmed-again — disarmed probes add zero HLO
    ops, armed probes are identity on the data path. The armed leg is
    verified to have actually probed (the silent trap is an armed
    wrapper reusing disarmed jit caches and measuring nothing)."""
    b, recipe, key = small
    before = _cube_sha(b, recipe, key)
    numerics.arm()
    armed = _cube_sha(b, recipe, key)
    numerics.flush()
    sites = numerics.snapshot()["sites"]
    assert any(s.startswith("realization.") for s in sites), sites
    numerics.disarm()
    after = _cube_sha(b, recipe, key)
    assert before == armed == after


def test_probe_disarmed_is_the_object_itself():
    x = jnp.arange(4.0)
    assert numerics.probe("anything", x) is x
    ints = jnp.arange(4)           # non-float: passthrough even armed
    numerics.arm(clear_caches=False)
    assert numerics.probe("ints", ints) is ints
    assert "ints" not in numerics.snapshot()["sites"]


# ----------------------------------------------- collector (donated stats)

def test_collector_stats_fold_at_the_drain(small):
    """The flagship transport: armed realize() stages per-site stats as
    extra engine outputs and stashes the un-fetched device scalars;
    flush()/the drain fold them into the ledger with EXACT per-site
    element accounting (slab elements x realizations)."""
    b, recipe, key = small
    nreal = 8
    numerics.arm()
    realize(key, b, recipe, nreal=nreal)
    numerics.flush()
    doc = numerics.snapshot()
    white = doc["sites"]["realization.white"]
    assert white["calls"] == 1
    # (3, 64) per realization = 192 elements, under the collector cap:
    # the whole family output of every realization was scanned
    assert white["elements"] == 3 * 64 * nreal
    assert white["nonfinite"] == 0 and doc["nonfinite_total"] == 0
    # the suite runs under x64 (conftest), so the engine's family
    # outputs are f64 here; the ledger records whatever dtype flowed
    assert white["max_abs"] > 0 and white["dtype"].startswith("float")
    finfo_max = np.finfo(np.dtype(white["dtype"])).max
    assert white["headroom_bits"] == pytest.approx(
        np.log2(finfo_max) - np.log2(white["max_abs"]))
    assert "realization.red" in doc["sites"]


def math_log2_f32max():
    return float(np.log2(np.finfo(np.float32).max))


def test_collector_slab_respects_the_cap():
    """One oversized invocation scans only the leading slab — the cap
    is what keeps armed probes off the flagship step's critical path
    (< 1% gated in benchmarks/numerics_probe.py)."""
    col = numerics.Collector()
    big = jnp.ones((64, 4096), jnp.float32)
    col.add("cap.site", big)
    col.take()
    scanned = numerics._SITE_META["cap.site"][0]
    assert scanned <= numerics.PROBE_SAMPLE_CAP_COLLECT
    assert scanned > 0


# --------------------------------------------------- episodes + watermarks

def test_episode_opens_on_nonfinite_and_clears_after_streak():
    numerics.arm(clear_caches=False)
    bad = jnp.array([1.0, jnp.nan, jnp.inf], jnp.float32)
    numerics.probe("realization.white", bad)
    numerics.flush()
    doc = numerics.snapshot()
    site = doc["sites"]["realization.white"]
    assert site["nonfinite"] == 2 and site["episodes"] == 1
    assert doc["episodes_active"] == ["realization.white"]

    clean = jnp.ones(3, jnp.float32)
    for _ in range(numerics.EPISODE_CLEAR_AFTER - 1):
        numerics.probe("realization.white", clean)
    numerics.flush()
    assert numerics.snapshot()["episodes_active"] == ["realization.white"]
    numerics.probe("realization.white", clean)
    numerics.flush()
    doc = numerics.snapshot()
    assert doc["episodes_active"] == []
    assert doc["sites"]["realization.white"]["episodes"] == 1  # closed, kept


def test_watermarks_track_overflow_margin():
    numerics.arm(clear_caches=False)
    numerics.probe("solver.winv_diag",
                   jnp.array([1e30, -2.0, 1e-20, 0.0], jnp.float32))
    numerics.flush()
    rec = numerics.snapshot()["sites"]["solver.winv_diag"]
    assert rec["max_abs"] == pytest.approx(1e30, rel=1e-6)
    assert rec["min_nonzero"] == pytest.approx(1e-20, rel=1e-6)
    # f32 overflows at 2**~128: ~28 bits of margin left above 1e30
    assert rec["headroom_bits"] == pytest.approx(28.3, abs=0.5)


def test_scan_block_is_the_post_device_last_line():
    """The drain scan catches corruption the in-graph probes cannot see
    (a fault-injected nan lands AFTER device compute — the bench's
    planted-NaN arm pins the attribution end to end)."""
    numerics.arm(clear_caches=False)
    block = np.ones((4, 8), np.float32)
    block[1, 3] = np.nan
    assert numerics.scan_block("drain", block) == 1
    rec = numerics.snapshot()["sites"]["drain"]
    assert rec["nonfinite"] == 1 and rec["elements"] == 32
    assert numerics.scan_block("drain", np.ones(4, np.float32)) == 0


# -------------------------------------------------- callback-mode fallback

def test_callback_probe_is_jit_vmap_and_grad_safe():
    """Non-collector graphs (likelihood/fit, mesh shards) use the
    callback emitter: identity output, one callback per engine call
    under vmap, and grads flow through probed values unchanged."""
    numerics.arm(clear_caches=False)

    @jax.jit
    def f(x):
        return jnp.sum(numerics.probe("gp.chol_rank", x) ** 2)

    x = jnp.arange(1.0, 5.0)
    assert float(f(x)) == pytest.approx(float(jnp.sum(x ** 2)))
    g = jax.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g), 2.0 * np.asarray(x))

    batched = jax.vmap(lambda v: numerics.probe("vmapped", v).sum())(
        jnp.ones((5, 3)))
    assert batched.shape == (5,)
    numerics.flush()
    doc = numerics.snapshot()
    assert doc["sites"]["gp.chol_rank"]["calls"] >= 1
    assert doc["sites"]["vmapped"]["calls"] == 1  # one per engine call


def test_arm_from_env(monkeypatch):
    assert not numerics.arm_from_env({})
    assert numerics.arm_from_env(
        {"PTA_NUMERICS": "1", "PTA_NUMERICS_DRIFT_EVERY": "5",
         "PTA_NUMERICS_SEED": "3"})
    assert numerics.is_armed()
    assert numerics.drift_offset() < 5
    # seeded: the sampled offset is a pure function of the seed
    assert numerics.drift_offset(5, 3) == numerics.drift_offset(5, 3)


# ----------------------------------------------------------------- drift

def test_drain_hook_samples_drift_within_tolerance(small):
    b, recipe, key = small
    numerics.arm(drift_every=1, clear_caches=False)
    numerics.on_drain(0, block=np.ones((2, 3, 64), np.float32),
                      batch=b, recipe=recipe, key=key, nreal=4)
    drift = numerics.snapshot()["drift"]
    assert drift, "sampled chunk recorded no families"
    for family, rec in drift.items():
        assert rec["samples"] == 1
        assert rec["tolerance"] is not None
        assert rec["worst"] <= rec["tolerance"], (family, rec)


# ------------------------------------------------- ledger + report + CLI

def test_numerics_json_roundtrips_through_the_schema_checker(
        small, tmp_path, capsys):
    b, recipe, key = small
    numerics.arm(drift_every=1)
    realize(key, b, recipe, nreal=4)
    numerics.on_drain(0, batch=b, recipe=recipe, key=key, nreal=4)
    numerics.flush()
    path = numerics.write(str(tmp_path))
    assert os.path.basename(path) == "numerics.json"

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_telemetry_schema import validate_numerics_file
    finally:
        sys.path.pop(0)
    assert validate_numerics_file(path) == []

    with open(path) as fh:
        doc = json.load(fh)
    assert doc["schema_version"] == numerics.NUMERICS_SCHEMA_VERSION
    assert doc["sites"] and doc["drift"]

    from pta_replicator_tpu.__main__ import main
    main(["numerics", "report", str(tmp_path)])
    out = capsys.readouterr().out
    assert "realization.white" in out
    assert "ladder readiness" in out


def test_report_names_a_never_armed_capture(tmp_path):
    text = numerics.render_report(str(tmp_path))
    assert "no numerics.json" in text and "PTA_NUMERICS" in text


def test_ladder_verdict_judges_all_three_legs():
    doc = {
        "sites": {
            "solver.winv_diag": {        # no family: headroom+nf only
                "nonfinite": 0, "headroom_bits": 20.0},
            "cov.blocked_pivot": {"nonfinite": 3, "headroom_bits": 30.0},
            "realization.white": {"nonfinite": 0, "headroom_bits": 2.0},
            "realization.red": {"nonfinite": 0, "headroom_bits": 12.0},
            "realization.gwb": {"nonfinite": 0, "headroom_bits": 12.0},
        },
        "drift": {
            "red": {"worst": 1e-5, "tolerance": 3e-3},
            "gwb": {"worst": 0.5, "tolerance": 3e-3},
        },
    }
    v = numerics.ladder_verdict(doc)
    assert v["solver.winv_diag"]["ready"]
    assert not v["cov.blocked_pivot"]["ready"]       # non-finites
    assert not v["realization.white"]["ready"]       # thin headroom +
    assert any("no drift samples" in r                # unsampled family
               for r in v["realization.white"]["reasons"])
    assert v["realization.red"]["ready"]
    assert not v["realization.gwb"]["ready"]         # drift over tol
    assert any("drift" in r for r in v["realization.gwb"]["reasons"])


def test_heartbeat_block_is_compact_and_truthful():
    numerics.arm(clear_caches=False)
    numerics.probe("realization.white",
                   jnp.array([jnp.nan, 1e10], jnp.float32))
    numerics.flush()
    hb = numerics.heartbeat_block()
    assert hb["armed"] and hb["nonfinite"] == 1
    assert hb["episodes_active"] == 1
    assert hb["worst_headroom_bits"] == pytest.approx(
        math_log2_f32max() - np.log2(1e10), abs=1e-6)
