"""Telemetry subsystem: spans, metrics, JAX accounting, report CLI.

CPU-only and fixture-free (pulsar datasets are fabricated in-process), so
this file runs everywhere tier-1 runs.
"""
import json
import os
import warnings

import numpy as np
import pytest

from pta_replicator_tpu import obs
from pta_replicator_tpu.obs.metrics import MetricsRegistry
from pta_replicator_tpu.obs.trace import EVENT_SCHEMA, Tracer


@pytest.fixture(autouse=True)
def _fresh_globals():
    """Each test sees empty global tracer buffers / metrics registry."""
    obs.reset_all()
    yield
    obs.configure(None)
    obs.reset_all()


# ---------------------------------------------------------------- spans
def test_span_nesting_paths_and_summary():
    t = Tracer()
    with t.span("outer", run=1):
        with t.span("inner"):
            pass
        with t.span("inner"):
            pass
    s = t.summary()
    assert set(s) == {"outer", "outer/inner"}
    assert s["outer"]["calls"] == 1
    assert s["outer/inner"]["calls"] == 2
    # the parent's wall time contains its children's
    assert s["outer"]["total_s"] >= s["outer/inner"]["total_s"]


def test_span_attrs_mutable_inside():
    t = Tracer()
    with t.span("stage", npsr=3) as sp:
        sp["result"] = "ok"
    rec = [e for e in t.events() if e["type"] == "span"][0]
    assert rec["attrs"] == {"npsr": 3, "result": "ok"}


def test_jsonl_sink_roundtrip_and_schema(tmp_path):
    t = Tracer()
    t.configure(str(tmp_path))
    with t.span("a", k="v"):
        with t.span("b"):
            pass
    t.event("marker", n=2)
    t.configure(None)  # close the sink

    lines = [
        json.loads(l)
        for l in (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    kinds = [r["type"] for r in lines]
    assert kinds[0] == "meta" and kinds.count("span") == 2
    for rec in lines:
        for field, ftype in EVENT_SCHEMA[rec["type"]].items():
            assert field in rec
            if ftype is float:
                assert isinstance(rec[field], (int, float))
            else:
                assert isinstance(rec[field], ftype)
    # spans written at completion: child precedes parent in the stream
    spans = [r for r in lines if r["type"] == "span"]
    assert [s["path"] for s in spans] == ["a/b", "a"]


def test_chrome_trace_export():
    t = Tracer()
    with t.span("x"):
        pass
    ct = t.chrome_trace()
    (ev,) = ct["traceEvents"]
    assert ev["ph"] == "X" and ev["name"] == "x" and ev["dur"] >= 0


def test_start_capture_resets_state(tmp_path):
    """Back-to-back captures in one process: the second dir's metrics and
    chrome trace must describe only the second run."""
    d1, d2 = tmp_path / "one", tmp_path / "two"
    obs.start_capture(str(d1))
    with obs.span("first_run"):
        pass
    obs.counter("demo.count").inc(7)
    obs.finish_capture()
    obs.start_capture(str(d2))
    with obs.span("second_run"):
        pass
    obs.finish_capture()

    m2 = json.loads((d2 / "metrics.json").read_text())
    assert "demo.count" not in m2
    ct2 = json.loads((d2 / "chrome_trace.json").read_text())
    assert [e["name"] for e in ct2["traceEvents"]] == ["second_run"]
    # the first capture's artifacts are untouched
    m1 = json.loads((d1 / "metrics.json").read_text())
    assert m1["demo.count"][0]["value"] == 7


def test_reconfigure_truncates_previous_stream(tmp_path):
    """One capture dir describes one run: a second capture into the same
    dir must not merge with (and double-count against) the first."""
    t = Tracer()
    t.configure(str(tmp_path))
    with t.span("first_run"):
        pass
    t.configure(str(tmp_path))
    with t.span("second_run"):
        pass
    t.configure(None)
    text = (tmp_path / "events.jsonl").read_text()
    assert "second_run" in text and "first_run" not in text


def test_idle_event_buffer_is_bounded():
    t = Tracer()
    for _ in range(Tracer.IDLE_MAX_EVENTS + 50):
        with t.span("spin"):
            pass
    assert len(t.events()) == Tracer.IDLE_MAX_EVENTS
    assert t.dropped == 50
    # aggregation keeps counting past the buffer cap
    assert t.summary()["spin"]["calls"] == Tracer.IDLE_MAX_EVENTS + 50


def test_inherit_nests_worker_thread_spans():
    from concurrent.futures import ThreadPoolExecutor

    t = Tracer()
    with t.span("parent"):
        ctx = t.current_stack()

        def work():
            with t.inherit(ctx):
                with t.span("child"):
                    pass

        with ThreadPoolExecutor(max_workers=1) as pool:
            pool.submit(work).result()
    assert "parent/child" in t.summary()


# -------------------------------------------------------------- metrics
def test_metrics_json_and_prometheus_format():
    r = MetricsRegistry()
    r.counter("io.tim.toas").inc(122)
    r.counter("jax.trace_count", fn="engine").inc()
    r.gauge("mesh.devices").set(8)
    h = r.histogram("compile.s")
    h.observe(0.02)
    h.observe(3.0)

    j = r.to_json()
    assert j["io.tim.toas"][0]["value"] == 122
    assert j["compile.s"][0]["count"] == 2
    assert j["compile.s"][0]["min"] == 0.02 and j["compile.s"][0]["max"] == 3.0

    prom = r.to_prometheus()
    assert "# TYPE io_tim_toas counter" in prom
    assert "io_tim_toas 122.0" in prom
    assert 'jax_trace_count{fn="engine"} 1.0' in prom
    assert "# TYPE mesh_devices gauge" in prom
    assert "# TYPE compile_s histogram" in prom
    assert 'compile_s_bucket{le="+Inf"} 2' in prom
    assert "compile_s_count 2" in prom
    # cumulative bucket counts are monotone
    counts = [
        int(l.rsplit(" ", 1)[1])
        for l in prom.splitlines() if l.startswith("compile_s_bucket")
    ]
    assert counts == sorted(counts)


def test_metric_kind_collision_raises():
    r = MetricsRegistry()
    r.counter("x")
    with pytest.raises(TypeError, match="registered as counter"):
        r.gauge("x")


# ------------------------------------------------------ jax accounting
def test_jax_compile_counter_increments():
    import jax
    import jax.numpy as jnp

    assert obs.install_jax_hooks()
    before = obs.counter("jax.compiles").value
    # a fresh shape through a fresh jit always compiles at least once
    f = jax.jit(lambda x: (x * 3).sum())
    np.asarray(f(jnp.ones((7, 13))))
    assert obs.counter("jax.compiles").value > before
    assert obs.REGISTRY.histogram("jax.compile_s").count > 0


def test_retrace_warning_on_changed_static_arg():
    import jax.numpy as jnp

    calls = obs.instrumented_jit(
        lambda x, n: x * n, name="retrace_probe", retrace_warn=2,
        static_argnums=1,
    )
    x = jnp.ones(3)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for n in range(4):  # 4 distinct static args -> 4 traces
            np.asarray(calls(x, n))
    msgs = [w for w in caught if issubclass(w.category, obs.RetraceWarning)]
    assert len(msgs) == 2  # traces 3 and 4 exceed the threshold of 2
    assert "retrace_probe" in str(msgs[0].message)
    assert obs.trace_count("retrace_probe") == 4
    # cached call: no new trace, no new warning
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        np.asarray(calls(x, 3))
    assert not caught
    assert obs.trace_count("retrace_probe") == 4


def test_device_memory_snapshot_shape():
    import jax  # ensure jax is initialized so the snapshot is attempted

    jax.devices()
    snaps = obs.device_memory_snapshot()
    assert isinstance(snaps, list) and snaps  # conftest forces 8 cpu devs
    assert all("device" in s and "platform" in s for s in snaps)


def test_transfer_counters():
    obs.record_transfer(1024, "h2d")
    obs.record_transfer(512, "h2d")
    assert obs.counter("jax.transfer.h2d_bytes").value == 1536
    assert obs.counter("jax.transfer.h2d_count").value == 2
    with pytest.raises(ValueError):
        obs.record_transfer(1, "sideways")


# ------------------------------------------------- legacy profiling API
def test_profiling_shims_delegate_to_obs():
    from pta_replicator_tpu.utils.profiling import reset, stage, timings

    reset()
    with stage("demo"):
        with stage("sub"):
            pass
    with stage("demo"):
        pass
    t = timings()
    assert t["demo"]["calls"] == 2
    assert t["sub"]["calls"] == 1
    assert t["demo"]["total_s"] >= 0
    # the same data is visible as obs spans (nested path)
    assert "demo/sub" in obs.TRACER.summary()


# ------------------------------------------------ pipeline + report CLI
PAR_TEMPLATE = """PSR JFAKE0{i}
RAJ 0{i}:37:15.8
DECJ -{dec}:15:08.6
F0 173.6879458121843
F1 -1.728e-15
PEPOCH 53000
DM 2.64
"""


@pytest.fixture()
def fabricated_partim(tmp_path):
    """3 fabricated pulsars written as par/tim directories (no reference
    fixtures needed)."""
    import pta_replicator_tpu as ptr

    pardir = tmp_path / "par"
    timdir = tmp_path / "tim"
    pardir.mkdir()
    timdir.mkdir()
    mjds = np.linspace(53000.0, 53000.0 + 2 * 365.25, 64)
    for i in range(3):
        src = tmp_path / f"src{i}.par"
        src.write_text(PAR_TEMPLATE.format(i=i, dec=17 + 25 * i))
        psr = ptr.simulate_pulsar(str(src), mjds, 0.5)
        psr.write_partim(str(pardir / f"JFAKE{i:02d}.par"),
                         str(timdir / f"JFAKE{i:02d}.tim"))
    return str(pardir), str(timdir)


def test_cli_telemetry_capture_and_report(
    tmp_path, fabricated_partim, capsys
):
    """The acceptance path: realize --telemetry DIR, then report DIR —
    span tree with >= 5 distinct instrumented stages and nonzero
    jit-compile counters."""
    from pta_replicator_tpu.__main__ import main

    pardir, timdir = fabricated_partim
    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({
        "efac": 1.1, "rn_log10_amplitude": -14.0, "rn_gamma": 4.33,
    }))
    tdir = tmp_path / "telemetry"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "4",
          "--out", str(tmp_path / "res.npz"), "--fit",
          "--telemetry", str(tdir)])
    capsys.readouterr()

    for artifact in ("events.jsonl", "metrics.json", "metrics.prom",
                     "chrome_trace.json", "meta.json"):
        assert (tdir / artifact).exists()

    from pta_replicator_tpu.obs.report import aggregate_spans, load_telemetry

    data = load_telemetry(str(tdir))
    agg = aggregate_spans(data["events"])
    assert len(agg) >= 5, f"only {sorted(agg)} stages captured"
    for stage in ("realize", "realize/ingest", "realize/freeze",
                  "realize/compute"):
        assert stage in agg
    # pool-worker parse spans inherit the ingest ancestry (not roots)
    assert "realize/ingest/load_pulsars/read_tim" in agg
    jax_compiles = data["metrics"]["jax.compiles"][0]["value"]
    assert jax_compiles > 0

    main(["report", str(tdir)])
    text = capsys.readouterr().out
    assert "realize" in text and "compute" in text
    assert "jax.compiles" in text

    main(["report", str(tdir), "--json"])
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["spans"]["realize/compute"]["calls"] == 1


def test_schema_checker_passes_on_capture(tmp_path, fabricated_partim,
                                          capsys):
    """scripts/check_telemetry_schema.py: the fast CI wiring — validates
    both the static instrumentation coverage and a real captured stream."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)

    # no-arg mode: generated sample stream + entrypoint grep
    assert checker.main([]) == 0

    # captured-dir mode, against a real info run
    from pta_replicator_tpu.__main__ import main

    pardir, timdir = fabricated_partim
    tdir = tmp_path / "telemetry"
    main(["info", "--pardir", pardir, "--timdir", timdir,
          "--telemetry", str(tdir)])
    capsys.readouterr()
    assert checker.main([str(tdir)]) == 0

    # a corrupted stream (non-final line) is caught
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "events.jsonl").write_text('{"type": "span"}\nnot-json\n{}\n')
    assert checker.main([str(bad)]) == 1


def test_sweep_and_sharded_paths_record_spans(tmp_path, fabricated_partim,
                                              capsys):
    """The mesh + sweep engines leave their spans and transfer counters —
    the pipelined executor's dispatch/drain/io_write set by default, the
    synchronous sweep_chunk/readback_fence set at --pipeline-depth 1."""
    from pta_replicator_tpu.__main__ import main

    pardir, timdir = fabricated_partim
    recipe = tmp_path / "recipe.json"
    recipe.write_text(json.dumps({"efac": 1.0}))
    tdir = tmp_path / "telemetry"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "16", "--sharded",
          "--chunk", "8", "--checkpoint", str(tmp_path / "ck.npz"),
          "--out", str(tmp_path / "res.npz"), "--telemetry", str(tdir)])
    capsys.readouterr()

    from pta_replicator_tpu.obs.report import aggregate_spans, load_telemetry

    agg = aggregate_spans(load_telemetry(str(tdir))["events"])
    pipe_paths = [p for p in agg if p.endswith("sweep_pipeline")]
    assert pipe_paths and agg[pipe_paths[0]]["calls"] == 1
    for leaf in ("drain", "io_write"):
        paths = [p for p in agg if p.endswith(leaf)]
        assert paths, f"missing {leaf} spans"
        assert sum(agg[p]["calls"] for p in paths) == 2
        # worker threads inherit the sweep ancestry: the spans nest
        # under the pipeline phase, not at the root
        assert all("sweep_pipeline" in p for p in paths)
    assert any("sharded_realize" in p for p in agg)
    metrics = load_telemetry(str(tdir))["metrics"]
    assert metrics["jax.transfer.h2d_bytes"][0]["value"] > 0
    assert metrics["sweep.realizations"][0]["value"] == 16

    # depth 1: the synchronous loop's spans, unchanged from PR 1
    tdir1 = tmp_path / "telemetry_d1"
    main(["realize", "--pardir", pardir, "--timdir", timdir,
          "--recipe", str(recipe), "--nreal", "16", "--sharded",
          "--chunk", "8", "--pipeline-depth", "1",
          "--checkpoint", str(tmp_path / "ck1.npz"),
          "--out", str(tmp_path / "res1.npz"), "--telemetry", str(tdir1)])
    capsys.readouterr()
    agg1 = aggregate_spans(load_telemetry(str(tdir1))["events"])
    chunk_paths = [p for p in agg1 if p.endswith("sweep_chunk")]
    assert chunk_paths and agg1[chunk_paths[0]]["calls"] == 2
    assert any(p.endswith("readback_fence") for p in agg1)
    # identical physics: the two checkpoints must agree byte-for-byte
    assert (tmp_path / "ck.npz").read_bytes() == (
        tmp_path / "ck1.npz").read_bytes()


# ------------------------------------------------------- bench summary
def test_telemetry_summary_shape():
    with obs.span("stage_one"):
        pass
    obs.counter("jax.compiles").inc(3)
    s = obs.telemetry_summary()
    assert s["spans"]["stage_one"]["calls"] == 1
    assert s["jax"]["jax.compiles"] == 3


# ----------------------------------------------------- clock discipline
def test_backwards_wallclock_jump_cannot_negate_span_duration(monkeypatch):
    """Span durations come from the monotonic perf_counter, never from
    t0 arithmetic: a wall-clock step (NTP correction) mid-span must not
    produce a negative wall_s. time.time() survives only as the exported
    t0 timestamp (the invariant graftlint's thread-walltime-duration
    rule enforces tree-wide)."""
    import time as _time

    t = Tracer()
    wall = iter([1000.0, 400.0, 400.0])  # clock jumps 10 minutes back
    monkeypatch.setattr(_time, "time", lambda: next(wall, 400.0))
    with t.span("jumpy"):
        pass
    rec = [e for e in t.events() if e["type"] == "span"][0]
    assert rec["t0"] == 1000.0  # wall timestamp: exported as-is
    assert rec["wall_s"] >= 0.0
    assert rec["cpu_s"] >= 0.0
    assert t.summary()["jumpy"]["total_s"] >= 0.0
