"""Device-time attribution layer: occupancy math against hand-computed
interval fixtures, the live heartbeat bottleneck path (incl. the
torn-read hammer), devprof cost/roofline extraction, the managed
device-trace capture, report rendering + degradation, and the
bench-diff direction classes for the new names.

CPU-only and fixture-free; the devprof capture tests use real jax on
the CPU backend.
"""
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from pta_replicator_tpu import obs
from pta_replicator_tpu.obs import devprof, names, occupancy
from pta_replicator_tpu.obs.regress import metric_direction


@pytest.fixture(autouse=True)
def _fresh_globals():
    obs.reset_all()
    yield
    obs.configure(None)
    obs.reset_all()


def _span(name, t0, wall, tid=1):
    return {"type": "span", "name": name, "path": name, "t0": float(t0),
            "wall_s": float(wall), "cpu_s": 0.0, "tid": tid, "seq": 0,
            "attrs": {}}


# ------------------------------------------------------ interval math
def test_merge_and_busy_seconds_hand_computed():
    assert occupancy.merge_intervals([]) == []
    # overlapping + disjoint: union is [0,3] + [5,6] = 4 s
    assert occupancy.busy_seconds([(0, 2), (1, 3), (5, 6)]) == 4.0
    # nested and touching intervals
    assert occupancy.merge_intervals([(0, 10), (2, 3), (10, 12)]) == \
        [(0, 12)]


def test_analyze_hand_computed_duty_overlap_and_bottleneck():
    # drain busy [0,4]+[5,9] = 8 s; io_write busy [2,6]+[7,9] = 6 s;
    # window [0,9] = 9 s; serial = 14 s; longest = 8 s
    events = [_span("drain", 0, 4), _span("drain", 5, 4),
              _span("io_write", 2, 4), _span("io_write", 7, 2)]
    util = occupancy.analyze(events)
    assert util["wall_s"] == 9.0
    assert util["serial_s"] == 14.0
    assert util["stages"]["drain"] == {
        "calls": 2, "busy_s": 8.0, "duty": round(8 / 9, 3)}
    assert util["stages"]["io_write"]["duty"] == round(6 / 9, 3)
    # efficiency = (14 - 9) / (14 - 8)
    assert util["overlap_efficiency"] == round(5 / 6, 3)
    assert util["wall_reduction_vs_serial_pct"] == round(
        100 * (1 - 9 / 14), 1)
    assert util["bottleneck"] == "drain 89% busy -> readback-bound"


def test_analyze_window_prefers_phase_span():
    # stage spans cover [0,2] but the sweep_pipeline phase ran [0,10]:
    # duty must be computed over the PHASE wall, not the busy extent
    events = [_span("sweep_pipeline", 0, 10), _span("io_write", 0, 2)]
    util = occupancy.analyze(events)
    assert util["wall_s"] == 10.0
    assert util["stages"]["io_write"]["duty"] == 0.2


def test_analyze_clips_stages_to_the_phase_window():
    """One capture can hold several phases (bench.py's sweep A/B runs
    the pipelined arm AND the synchronous arm): stage spans outside the
    analyzed phase must not count as busy inside it."""
    events = [_span("sweep_pipeline", 0, 10), _span("drain", 1, 4),
              # the synchronous arm, entirely after the pipelined phase
              _span("sweep_chunk", 12, 15), _span("readback_fence", 27, 3)]
    util = occupancy.analyze(events)
    assert set(util["stages"]) == {"drain"}
    assert util["wall_s"] == 10.0
    assert util["stages"]["drain"]["duty"] == 0.4
    # an interval straddling the window edge is clipped, not dropped
    events.append(_span("io_write", 8, 5))  # [8, 13] -> [8, 10]
    util = occupancy.analyze(events)
    assert util["stages"]["io_write"]["busy_s"] == 2.0
    # every stage outside the window: no utilization at all
    assert occupancy.analyze(
        [_span("sweep_pipeline", 0, 10), _span("sweep_chunk", 12, 3)]
    ) is None


def test_analyze_never_fabricates_overlap_from_nested_fence():
    """The synchronous loop nests readback_fence INSIDE sweep_chunk:
    counting both into the serial counterfactual would report overlap
    for a loop that has none by construction."""
    events = [_span("sweep_chunk", 0, 10), _span("readback_fence", 6, 3)]
    util = occupancy.analyze(events)
    # serial counterfactual counts the fence once (inside its parent)
    assert util["serial_s"] == 10.0
    assert util["wall_reduction_vs_serial_pct"] == 0.0
    assert "overlap_efficiency" not in util  # single top-level stage
    # ...but the per-stage table still shows the fence share
    assert util["stages"]["readback_fence"]["duty"] == 0.3
    # and the verdict names the parent, never the nested sub-stage
    assert util["bottleneck"].startswith("sweep_chunk")
    assert occupancy.verdict(
        {"sweep_chunk": 0.9, "readback_fence": 0.95}
    ).startswith("sweep_chunk")
    # a fence WITHOUT its parent present (custom window) counts normally
    assert occupancy.overlap_stats({"readback_fence": 5.0}, 10.0)[
        "duty"
    ] == {"readback_fence": 0.5}


def test_analyze_degrades_to_none_without_stage_spans():
    assert occupancy.analyze([]) is None
    assert occupancy.analyze([_span("freeze", 0, 1)]) is None


def test_verdict_thresholds():
    assert occupancy.verdict({}) is None
    assert occupancy.verdict({"io_write": 0.92}) == \
        "io_write 92% busy -> disk-bound"
    assert occupancy.verdict({"cw_stream_stage": 0.8}) == \
        "cw_stream_stage 80% busy -> host-precompute-bound"
    assert occupancy.verdict({"drain": 0.1, "io_write": 0.05}) == \
        "all stages mostly idle"
    v = occupancy.verdict({"drain": 0.5, "io_write": 0.3})
    assert v.startswith("no single bottleneck")
    assert "drain" in v


def test_overlap_stats_fully_serial_and_ideal():
    # fully serial: wall == serial -> efficiency 0
    s = occupancy.overlap_stats({"a": 3.0, "b": 3.0}, 6.0)
    assert s["overlap_efficiency"] == 0.0
    # ideal pipelining: wall == longest stage -> efficiency 1
    s = occupancy.overlap_stats({"a": 3.0, "b": 6.0}, 6.0)
    assert s["overlap_efficiency"] == 1.0
    # one active stage: efficiency undefined, not crashed
    s = occupancy.overlap_stats({"a": 3.0, "b": 0.0}, 4.0)
    assert "overlap_efficiency" not in s
    assert occupancy.overlap_stats({}, 1.0) == {}


# --------------------------------------------------- live StageOccupancy
def test_stage_occupancy_live_snapshot_and_bottleneck():
    occ = occupancy.StageOccupancy(window_s=60.0)
    t0 = time.monotonic() - occ._t0  # noqa: F841 — recorder just built
    # simulate a saturated writer: busy ~= the recorder's lifetime
    time.sleep(0.05)
    lifetime = time.monotonic() - occ._t0
    occ.observe(_span("io_write", 0, lifetime))
    snap = occ.snapshot()
    assert snap["stages"]["io_write"] >= 0.75
    assert "disk-bound" in snap["bottleneck"]
    # non-stage spans and events are ignored
    occ.observe(_span("freeze", 0, 100))
    occ.observe({"type": "event", "name": "io_write"})
    assert "freeze" not in occ.snapshot()["stages"]


def test_stage_occupancy_empty_snapshot():
    snap = occupancy.StageOccupancy().snapshot()
    assert snap == {"stages": {}, "bottleneck": None}


def test_stage_occupancy_unions_concurrent_same_stage_spans():
    """N per-device cw_stream_stage spans (prefetch_to_mesh's stagers)
    overlap in time; live duty is their interval UNION, like the
    post-hoc analyze() path — summing would read as saturated (duty
    1.0) and steal the bottleneck verdict from the truly busy stage."""
    occ = occupancy.StageOccupancy(window_s=60.0)
    time.sleep(0.1)
    lifetime = time.monotonic() - occ._t0
    # 8 concurrent stagers, each busy the same ~half of the horizon:
    # observe() stamps all of them "ending now"
    for tid in range(8):
        occ.observe(_span("cw_stream_stage", 0, 0.5 * lifetime, tid=tid))
    duty = occ.snapshot()["stages"]["cw_stream_stage"]
    assert 0.3 <= duty <= 0.75, duty  # union ~0.5; a sum would clamp to 1.0


# -------------------------------------------------------- pipeline stats
def test_run_pipelined_reports_stage_busy_and_occupancy(tmp_path):
    from pta_replicator_tpu.parallel.pipeline import run_pipelined

    def dispatch(i):
        time.sleep(0.01)
        return np.full(4, i)

    def write(i, block):
        time.sleep(0.03)
        np.save(tmp_path / f"c{i}.npy", block)

    stats = run_pipelined(range(4), dispatch, write, depth=2)
    busy = stats["stage_busy_s"]
    assert set(busy) == {"dispatch", "drain", "io_write"}
    assert busy["io_write"] >= 4 * 0.03 * 0.9
    occ = stats["occupancy"]
    assert occ["bottleneck"]
    assert 0.0 <= occ.get("overlap_efficiency", 0.0) <= 1.0
    # stage_busy_s values are rounded for the JSON; compare loosely
    assert occ["serial_s"] == pytest.approx(sum(busy.values()), abs=1e-5)


def test_synchronous_sweep_attributes_disk_time(tmp_path):
    """The depth-1 loop's checkpoint write carries the same io_write
    stage span as the pipelined writer thread, so an I/O-bound
    synchronous run cannot read as compute-bound."""
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.utils.sweep import sweep

    batch = synthetic_batch(npsr=2, ntoa=128, seed=0)
    recipe = Recipe(efac=jnp.ones(2, batch.toas_s.dtype))
    sweep(jax.random.PRNGKey(0), batch, recipe, nreal=8, chunk=4,
          checkpoint_path=str(tmp_path / "s.npz"), pipeline_depth=1)
    util = occupancy.analyze(obs.TRACER.events())
    assert "io_write" in util["stages"]
    assert util["stages"]["io_write"]["calls"] == 2


# ---------------------------------------------------- heartbeat + watch
def test_heartbeat_carries_occupancy_and_watch_prints_bottleneck(tmp_path):
    from pta_replicator_tpu.obs.flightrec import (
        PROGRESS_SCHEMA,
        FlightRecorder,
    )
    from pta_replicator_tpu.obs.report import render_heartbeat

    rec = FlightRecorder(str(tmp_path), interval_s=5.0,
                         stall_timeout_s=None).start()
    try:
        time.sleep(0.05)
        # duty = busy / recorder lifetime: a span ~9x the pre-span
        # lifetime leaves duty ~0.9 however slow the host is
        lifetime = time.monotonic() - rec.occupancy._t0
        with obs.span("io_write"):
            time.sleep(min(2.0, lifetime * 9.0))
        hb = rec.write_heartbeat()
    finally:
        rec.stop()
    assert "occupancy" in PROGRESS_SCHEMA
    occ = hb["occupancy"]
    assert occ["stages"]["io_write"] > 0.5
    assert "disk-bound" in occ["bottleneck"]
    # the duty gauges mirror into the registry for metrics.json
    assert obs.REGISTRY.gauge(
        names.OCCUPANCY_DUTY_CYCLE, stage="io_write"
    ).value > 0.5
    line = render_heartbeat(hb)
    assert "disk-bound" in line
    # a v1-era heartbeat without the block still renders
    assert "disk-bound" not in render_heartbeat(
        {"written_at": "x", "finished": False})


def test_heartbeat_with_occupancy_valid_under_torn_read_hammer(tmp_path):
    """Satellite: the heartbeat grew the occupancy block — the
    atomic-replace contract must still hold while stage spans hammer
    the recorder (readers never see a torn or partial document)."""
    from pta_replicator_tpu.obs.flightrec import FlightRecorder

    rec = FlightRecorder(str(tmp_path), interval_s=0.001,
                         stall_timeout_s=None).start()
    path = tmp_path / "progress.json"
    deadline = time.monotonic() + 5.0
    while not path.exists() and time.monotonic() < deadline:
        time.sleep(0.01)
    failures = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                doc = json.loads(path.read_text())
                if "occupancy" not in doc:
                    failures.append("heartbeat missing occupancy block")
            except json.JSONDecodeError as exc:
                failures.append(repr(exc))
            except FileNotFoundError:
                failures.append("file vanished")

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    while time.monotonic() - t0 < 1.0:
        with obs.span("drain"):
            pass
        with obs.span("io_write"):
            pass
    stop.set()
    for t in threads:
        t.join()
    rec.stop()
    assert not failures, failures[:5]


# ------------------------------------------------------------- devprof
class _FakeMem:
    argument_size_in_bytes = 100
    output_size_in_bytes = 50
    temp_size_in_bytes = 10
    alias_size_in_bytes = 0
    generated_code_size_in_bytes = 5


class _FakeCompiled:
    def __init__(self, flops=2e9, nbytes=1e8):
        self._flops, self._bytes = flops, nbytes
        self.cost_calls = 0

    def cost_analysis(self):
        self.cost_calls += 1
        return [{"flops": self._flops, "bytes accessed": self._bytes,
                 "transcendentals": 3.0, "bytes accessed0{}": 1.0}]

    def memory_analysis(self):
        return _FakeMem()


class _BrokenCompiled:
    def cost_analysis(self):
        raise RuntimeError("backend does not report")

    def memory_analysis(self):
        raise RuntimeError("nope")


def test_extract_cost_and_memory_normalized():
    cost = devprof.extract_cost(_FakeCompiled())
    assert cost == {"flops": 2e9, "bytes_accessed": 1e8,
                    "transcendentals": 3.0}
    mem = devprof.extract_memory(_FakeCompiled())
    assert mem["argument_bytes"] == 100 and mem["temp_bytes"] == 10
    assert devprof.extract_cost(_BrokenCompiled()) == {}
    assert devprof.extract_memory(_BrokenCompiled()) == {}
    with pytest.raises(RuntimeError):
        devprof.extract_cost(_BrokenCompiled(), strict=True)


def test_record_compiled_sets_gauges_cached_per_compilation():
    c = _FakeCompiled()
    out = devprof.record_compiled("lbl", c)
    assert out["flops"] == 2e9
    g = obs.REGISTRY.gauge("jax.cost.flops", label="lbl")
    assert g.value == 2e9
    # same executable again: served from the cache without re-invoking
    # cost_analysis()
    assert devprof.record_compiled("lbl", c)["flops"] == 2e9
    assert c.cost_calls == 1
    # a NEW compilation under the same label overwrites
    devprof.record_compiled("lbl", _FakeCompiled(flops=5e9))
    assert g.value == 5e9


def test_roofline_classification_and_gauges():
    # v5e ridge = 197e12 / 819e9 ~= 240 flop/B
    roof = devprof.roofline(
        "mem", flops=2e9, bytes_accessed=1e8, elapsed_s=0.01, calls=10,
        device_kind="TPU v5 lite",
    )
    assert roof["flops_per_s"] == pytest.approx(2e12)
    assert roof["intensity_flop_per_byte"] == pytest.approx(20.0)
    assert roof["bound"] == "memory-bound"
    assert devprof.classify(300.0, roof["ridge_intensity"]) == \
        "compute-bound"
    # below the ridge the attainable rate is bandwidth-limited
    attainable = 20.0 * 819e9
    assert roof["pct_of_roofline"] == pytest.approx(
        100 * 2e12 / attainable)
    assert obs.REGISTRY.gauge(
        "jax.roofline.ridge_intensity", label="mem"
    ).value == pytest.approx(197e12 / 819e9)
    # unknown backend: achieved + intensity only, no peak-relative keys
    roof = devprof.roofline(
        "cpu", flops=1e9, bytes_accessed=1e9, elapsed_s=1.0,
        device_kind="weird accelerator",
    )
    assert "pct_of_roofline" not in roof and "bound" not in roof
    assert roof["flops_per_s"] == pytest.approx(1e9)


def test_peak_for_env_override(monkeypatch):
    monkeypatch.setenv("DEVPROF_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("DEVPROF_PEAK_BYTES_PER_S", "1e11")
    assert devprof.peak_for("anything") == (1e12, 1e11)
    # a half-set override warns instead of silently dropping the peak
    monkeypatch.delenv("DEVPROF_PEAK_FLOPS")
    with pytest.warns(UserWarning, match="both env vars"):
        assert devprof.peak_for("nope") is None
    with pytest.warns(UserWarning):
        assert devprof.peak_for("TPU v4") == (275e12, 1228e9)
    monkeypatch.delenv("DEVPROF_PEAK_BYTES_PER_S")
    assert devprof.peak_for("TPU v4") == (275e12, 1228e9)
    assert devprof.peak_for("nope") is None


def test_bench_cost_fields_schema_and_error_path():
    out = devprof.bench_cost_fields(
        _FakeCompiled(), reps=5, elapsed_s=0.5,
        device_kind="TPU v5 lite", label="bench.test",
    )
    assert out["xla_flops_per_chunk"] == 2e9
    assert out["achieved_tflops_per_s"] == pytest.approx(
        2e9 * 5 / 0.5 / 1e12, rel=1e-3)
    assert out["roofline_bound"] == "memory-bound"
    assert "mfu_vs_bf16_peak_pct" in out and "pct_of_roofline" in out
    # a backend whose cost_analysis() RAISES yields the historical
    # cost_analysis_error marker (never an exception out of a bench) —
    # distinguishable from a backend that merely reports no cost model
    broken = devprof.bench_cost_fields(
        _BrokenCompiled(), reps=1, elapsed_s=1.0)
    assert "RuntimeError" in broken["cost_analysis_error"]
    assert "cost_analysis_error" in devprof.bench_cost_fields(
        None, reps=1, elapsed_s=1.0)

    class _NoCostModel:  # reports an empty model: empty block, no error
        def cost_analysis(self):
            return [{}]

        def memory_analysis(self):
            return None

    assert devprof.bench_cost_fields(
        _NoCostModel(), reps=1, elapsed_s=1.0) == {}


def test_instrumented_jit_pending_capture_cpu():
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.obs.jaxhooks import instrumented_jit

    f = instrumented_jit(lambda x: (x * 2.0).sum(), name="occ.test_fn")
    f(jnp.ones(16)).block_until_ready()
    captured = devprof.capture_pending()
    assert "occ.test_fn" in captured
    assert captured["occ.test_fn"]["flops"] > 0
    assert obs.REGISTRY.gauge(
        "jax.cost.flops", label="occ.test_fn").value > 0
    # nothing pending after a capture (no retrace happened)
    assert devprof.capture_pending() == {}
    # a retrace (new shape) re-arms the pending set
    f(jnp.ones(32)).block_until_ready()
    assert "occ.test_fn" in devprof.capture_pending(force=True)


def test_capture_pending_pairs_avals_with_their_own_instance():
    """Several jit instances may share one label (the lru_cached mesh
    engines): the pending avals must be lowered from the instance that
    produced them, not from whichever instance registered last."""
    import jax.numpy as jnp

    from pta_replicator_tpu.obs.jaxhooks import instrumented_jit

    small = instrumented_jit(lambda x: x * 2.0, name="occ.shared")
    big = instrumented_jit(lambda m: (m @ m).sum(), name="occ.shared")
    # trace big first, then small: the label's pending slot holds
    # SMALL's avals and must lower SMALL (big's matmul would be ~1000x
    # the flops — and lowering big from a 1-D aval would just raise and
    # silently record nothing)
    big(jnp.ones((32, 32))).block_until_ready()
    small(jnp.ones(8)).block_until_ready()
    captured = devprof.capture_pending(force=True)
    assert "occ.shared" in captured
    assert 0 < captured["occ.shared"]["flops"] < 100


def test_capture_pending_does_not_perturb_retrace_accounting():
    """The synthetic lowering strips weak_type, which can genuinely
    retrace a label called with Python scalars — the measurement must
    not count as a retrace nor re-arm the pending set it drains."""
    import warnings

    import jax.numpy as jnp

    from pta_replicator_tpu.obs import trace_count
    from pta_replicator_tpu.obs.jaxhooks import instrumented_jit

    obs.install_jax_hooks()
    g = instrumented_jit(lambda x, s: x * s, name="occ.weak",
                         retrace_warn=1)
    g(jnp.ones(4), 2.0).block_until_ready()  # weak-typed scalar arg
    assert trace_count("occ.weak") == 1
    compiles_before = obs.REGISTRY.counter("jax.compiles").value
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a RetraceWarning would raise
        captured = devprof.capture_pending()
    assert "occ.weak" in captured
    assert trace_count("occ.weak") == 1  # the probe ignored the probe
    # ...and the synthetic compile stayed out of the compile accounting
    assert obs.REGISTRY.counter("jax.compiles").value == compiles_before
    assert devprof.capture_pending() == {}  # pending set drained


def test_duty_gauge_zeroed_when_stage_leaves_window(tmp_path):
    from pta_replicator_tpu.obs.flightrec import FlightRecorder

    rec = FlightRecorder(str(tmp_path), interval_s=5.0,
                         stall_timeout_s=None).start()
    try:
        rec.occupancy.window_s = 0.15
        with obs.span("io_write"):
            time.sleep(0.1)
        rec.write_heartbeat()
        g = obs.REGISTRY.gauge(names.OCCUPANCY_DUTY_CYCLE,
                               stage="io_write")
        assert g.value > 0.0
        time.sleep(0.3)  # the write drops out of the rolling window
        hb = rec.write_heartbeat()
        assert "io_write" not in hb["occupancy"]["stages"]
        assert g.value == 0.0  # stale saturation must not linger
        # ...and the zeroing happens once, not on every later tick
        g.set(0.5)
        rec.write_heartbeat()
        assert g.value == 0.5
    finally:
        rec.stop()


def test_device_trace_registers_capture_artifact(tmp_path):
    import jax.numpy as jnp

    d = str(tmp_path / "cap")
    obs.start_capture(d, flight_recorder=False)
    with devprof.device_trace() as logdir:
        jnp.ones(8).sum().block_until_ready()
    obs.finish_capture()
    assert os.path.isdir(logdir)
    meta = json.loads((tmp_path / "cap" / "meta.json").read_text())
    assert meta["device_traces"] == ["xla_trace"]  # relativized
    # the completion event landed in the stream
    evs = (tmp_path / "cap" / "events.jsonl").read_text()
    assert "devprof.device_trace" in evs and '"device_trace"' in evs

    from pta_replicator_tpu.obs.report import render_report

    out = render_report(d)
    assert "device trace: xla_trace" in out

    # schema checker: registered dirs must exist
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    assert checker.validate_device_traces(d) == []
    import shutil

    shutil.rmtree(logdir)
    problems = checker.validate_device_traces(d)
    assert problems and "does not exist" in problems[0]


def test_schema_checker_tolerates_v1_heartbeats(tmp_path):
    """PROGRESS_SCHEMA v2 added the required occupancy block; a capture
    written by the v1 recorder must still validate (the field is only
    required from the document's own schema stamp on)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)

    v1 = {"schema": 1, "pid": 1, "written_at": "x", "uptime_s": 0.1,
          "last_span_age_s": 0.1, "open_spans": {}, "sweep": {},
          "jax": {}, "stalls": 0.0, "finished": True}
    p = tmp_path / "progress.json"
    p.write_text(json.dumps(v1))
    assert checker.validate_flightrec_file(str(p), "progress") == []
    # a v2 document missing the block is still an error
    p.write_text(json.dumps({**v1, "schema": 2}))
    problems = checker.validate_flightrec_file(str(p), "progress")
    assert problems and "occupancy" in problems[0]


def test_device_trace_requires_capture_or_logdir():
    with pytest.raises(ValueError, match="no telemetry capture"):
        with devprof.device_trace():
            pass


def test_profiling_shim_delegates_to_devprof(tmp_path):
    import jax.numpy as jnp

    from pta_replicator_tpu.utils.profiling import device_trace

    d = str(tmp_path / "cap")
    obs.start_capture(d, flight_recorder=False)
    with device_trace(str(tmp_path / "xla")):
        jnp.ones(4).sum().block_until_ready()
    obs.finish_capture()
    meta = json.loads((tmp_path / "cap" / "meta.json").read_text())
    # explicit logdir outside the capture dir stays absolute
    assert meta["device_traces"] == [str(tmp_path / "xla")]


# ------------------------------------------------------ report rendering
def test_report_renders_utilization_and_roofline(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    d = tmp_path / "cap"
    d.mkdir()
    with open(d / "events.jsonl", "w") as fh:
        fh.write(json.dumps(
            {"type": "meta", "schema": 1, "t0": 0.0}) + "\n")
        for rec in (_span("drain", 0, 8), _span("io_write", 1, 8.5)):
            fh.write(json.dumps(rec) + "\n")
    (d / "metrics.json").write_text(json.dumps({
        "jax.roofline.flops_per_s": [
            {"kind": "gauge", "labels": {"label": "bench.run_chunk"},
             "value": 2e12}],
        "jax.roofline.intensity_flop_per_byte": [
            {"kind": "gauge", "labels": {"label": "bench.run_chunk"},
             "value": 20.0}],
        "jax.roofline.ridge_intensity": [
            {"kind": "gauge", "labels": {"label": "bench.run_chunk"},
             "value": 240.0}],
        "jax.roofline.pct_of_roofline": [
            {"kind": "gauge", "labels": {"label": "bench.run_chunk"},
             "value": 12.2}],
    }))
    main(["report", str(d)])
    out = capsys.readouterr().out
    assert "utilization (stage occupancy):" in out
    assert "io_write" in out and "duty" in out
    assert "bottleneck:" in out
    assert "roofline (per jit label):" in out
    assert "memory-bound" in out and "12.2% of roofline" in out

    # degraded: a capture with no stage spans simply has no section
    empty = tmp_path / "plain"
    empty.mkdir()
    (empty / "events.jsonl").write_text(
        json.dumps({"type": "meta", "schema": 1, "t0": 0.0}) + "\n"
        + json.dumps(_span("freeze", 0, 1)) + "\n")
    main(["report", str(empty)])
    out = capsys.readouterr().out
    assert "utilization" not in out
    assert "roofline" not in out


def test_report_json_includes_utilization(tmp_path):
    from pta_replicator_tpu.obs.report import render_report

    d = tmp_path / "cap"
    d.mkdir()
    (d / "events.jsonl").write_text(json.dumps(_span("drain", 0, 2)) + "\n")
    doc = json.loads(render_report(str(d), as_json=True))
    assert doc["utilization"]["stages"]["drain"]["busy_s"] == 2.0


def test_chrome_trace_lifts_stage_spans_onto_named_tracks():
    from pta_replicator_tpu.obs.trace import Tracer

    tracer = Tracer()
    with tracer.span("drain"):  # graftlint: disable=telemetry-unknown-name
        pass
    with tracer.span("my_custom"):  # graftlint: disable=telemetry-unknown-name
        pass
    with tracer.span("dispatch"):  # graftlint: disable=telemetry-unknown-name
        pass
    doc = tracer.chrome_trace()
    metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    named = {e["tid"]: e["args"]["name"] for e in metas
             if e["name"] == "thread_name"}
    sort_index = {e["tid"]: e["args"]["sort_index"] for e in metas
                  if e["name"] == "thread_sort_index"}
    spans = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    # the stage span rides its named synthetic track...
    assert named[spans["drain"]["tid"]] == "stage:drain"
    # ...carrying an explicit sort_index in dataflow order (dispatch
    # before drain, whatever their tids or dict order say)
    assert set(sort_index) == set(named)
    assert sort_index[spans["dispatch"]["tid"]] < \
        sort_index[spans["drain"]["tid"]]
    # ...while a non-stage span keeps its real thread id
    assert spans["my_custom"]["tid"] == threading.get_ident()
    assert spans["my_custom"]["tid"] not in named


# ------------------------------------------------- bench-diff directions
def test_metric_direction_for_cost_roofline_and_occupancy_names():
    # jax.cost.* are program properties: never a perf verdict, even
    # though "flops" is a rate token elsewhere
    assert metric_direction("telemetry.jax.jax.cost.flops{label=x}") is None
    assert metric_direction("jax.cost.bytes_accessed") is None
    # roofline achieved rates and percentages are higher-better
    assert metric_direction(
        "telemetry.jax.jax.roofline.flops_per_s{label=bench.run_chunk}"
    ) is True
    assert metric_direction("pct_of_roofline") is True
    assert metric_direction("mfu_vs_bf16_peak_pct") is True
    # positions, not scores
    assert metric_direction("arithmetic_intensity_flop_per_byte") is None
    assert metric_direction("jax.roofline.ridge_intensity{label=x}") is None
    assert metric_direction("occupancy.duty_cycle{stage=drain}") is None
    # overlap metrics. wall_reduction_vs_serial is info, NOT
    # higher-better: the depth-1 null control records it at ~0, where a
    # relative-delta verdict turns noise (-0.2 -> -0.6) into
    # "regressed"; overlap_efficiency is the directional score
    assert metric_direction("measured_overlap_efficiency") is True
    assert metric_direction(
        "occupancy.depth1.wall_reduction_vs_serial_pct") is None
    assert metric_direction("wall_reduction_vs_serial_pct") is None
    assert metric_direction("stage_busy_s") is False
    assert metric_direction("cw_stream.prefetch_stall_s") is False


def test_bench_diff_accepts_new_names(tmp_path):
    from pta_replicator_tpu.obs.regress import bench_diff

    def doc(flops, tflops, pct):
        return {
            "metric": "m", "value": 100.0, "unit": "r/s",
            "schema_version": 2,
            "xla_flops_per_chunk": flops,
            "achieved_tflops_per_s": tflops,
            "pct_of_roofline": pct,
            "arithmetic_intensity_flop_per_byte": 20.0,
            "telemetry": {"jax": {
                "jax.cost.flops{label=bench.run_chunk}": flops,
                "jax.roofline.flops_per_s{label=bench.run_chunk}":
                    tflops * 1e12,
            }},
        }

    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(doc(1e9, 2.0, 10.0)))
    # flops halved (workload change: info), achieved rate halved
    # (regression), roofline % halved (regression)
    b.write_text(json.dumps(doc(5e8, 1.0, 5.0)))
    _table, summary, rc = bench_diff([str(a), str(b)], threshold=0.10)
    v = summary["verdicts"]
    assert v["xla_flops_per_chunk"] == "info"
    assert v["telemetry.jax.jax.cost.flops{label=bench.run_chunk}"] == \
        "info"
    assert v["achieved_tflops_per_s"] == "regressed"
    assert v["pct_of_roofline"] == "regressed"
    assert v["arithmetic_intensity_flop_per_byte"] == "info"
    assert rc == 1
