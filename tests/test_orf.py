"""ORF correctness: closed-form Hellings-Downs and the anisotropic basis.

The anisotropic basis is cross-validated against the reference
implementation imported from /root/reference (numerical oracle only — the
implementations are independent; this pins the BASELINE 'anisotropic GWB
via spharmORFbasis (l_max=4)' configuration).
"""
import sys

import numpy as np
import pytest

from pta_replicator_tpu.ops.orf import (
    angular_separation,
    assemble_orf,
    correlated_basis,
    hellings_downs,
    hellings_downs_matrix,
)


def _random_locs(n, seed=0):
    rng = np.random.default_rng(seed)
    phi = rng.uniform(0, 2 * np.pi, n)
    theta = np.arccos(rng.uniform(-1, 1, n))
    return np.stack([phi, theta], axis=1)


def test_hellings_downs_special_values():
    # Gamma(0+) = 1/2; antipodal: x=1 -> 1/2 - 1/4 + 0 = 1/4
    assert hellings_downs(1e-9) == pytest.approx(0.5, abs=1e-6)
    assert hellings_downs(np.pi) == pytest.approx(0.25)
    # 90 degrees: x = 1/2 -> 0.5 - 1/8 + 0.75*ln(1/2)
    expect = 0.5 - 0.125 + 0.75 * np.log(0.5)
    assert hellings_downs(np.pi / 2) == pytest.approx(expect)


def test_lmax0_basis_equals_closed_form():
    locs = _random_locs(6, seed=1)
    orf = assemble_orf(locs, lmax=0)
    hd = hellings_downs_matrix(locs)
    np.testing.assert_allclose(orf, hd, atol=1e-12)
    # symmetric positive definite (required by the Cholesky mix)
    np.testing.assert_allclose(orf, orf.T)
    assert np.linalg.eigvalsh(orf).min() > 0


@pytest.mark.skipif(
    not __import__("pathlib").Path("/root/reference/pta_replicator").is_dir(),
    reason="reference not mounted",
)
@pytest.mark.parametrize("lmax", [0, 1, 2, 4])
def test_anisotropic_basis_matches_reference(lmax):
    sys.path.insert(0, "/root/reference")
    try:
        from pta_replicator import spharmORFbasis as ref_anis
    finally:
        sys.path.pop(0)

    locs = _random_locs(4, seed=2)
    mine = correlated_basis(locs, lmax)
    theirs = np.array(ref_anis.correlated_basis(locs, lmax))
    assert mine.shape == theirs.shape == ((lmax + 1) ** 2, 4, 4)
    # the alternating factorial sums at l=4 carry ~1e-11 summation-order
    # rounding; 1e-9 absolute is far below any physical ORF scale (O(0.1))
    np.testing.assert_allclose(mine, theirs, rtol=1e-8, atol=1e-9)


def test_angular_separation():
    assert angular_separation(0.0, 0.0, 1.0, 1.0) == 0.0
    assert angular_separation(0.0, np.pi, np.pi / 2, np.pi / 2) == pytest.approx(np.pi)
