"""Regression parity against the reference's frozen libstempo fixture.

Replays the exact recipe of /root/reference/tests/test_against_libstempo.py
(GWB -14/4.33 seed 123456 -> EFAC 1.0 -> ECORR 3e-7 -> red noise -15/4.2,
30 modes, libstempo convention -> CGW) through this framework's CPU oracle
path, whose legacy-RNG draw order is draw-for-draw compatible with the
reference, and compares the (3, 122) residuals to the frozen libstempo
output. Tolerance is the reference's (1e-3 of total residual RMS) but
applied to |deviation| — the reference's signed comparison would pass
arbitrarily large negative deviations.
"""
import numpy as np
import pytest

from pta_replicator_tpu import (
    add_cgw,
    add_gwb,
    add_jitter,
    add_measurement_noise,
    add_red_noise,
    load_from_directories,
    make_ideal,
)

FIXTURE = "/root/reference/tests/libstempo_test_residuals_efac_ecorr_rn_gwb_cgw.npz"


@pytest.fixture(scope="module")
def full_stack_residuals(partim_small_module):
    pardir, timdir = partim_small_module
    psrs = load_from_directories(pardir, timdir, num_psrs=3)
    for psr in psrs:
        make_ideal(psr)

    add_gwb(psrs, -14, 4.33, seed=123456)

    seed_wn = 54321
    for ii, psr in enumerate(psrs):
        add_measurement_noise(psr, efac=1.00, log10_equad=None,
                              seed=seed_wn + ii, tnequad=False)
        add_jitter(psr, log10_ecorr=np.log10(3e-7), seed=seed_wn + ii)

    seed_rn = 12345
    for ii, psr in enumerate(psrs):
        add_red_noise(psr, -15, 4.2, components=30, Tspan=None,
                      seed=seed_rn + ii, libstempo_convention=True)

    for psr in psrs:
        add_cgw(psr, gwtheta=np.pi / 2, gwphi=2.5, mc=1e9, dist=5.0,
                fgw=1e-8, phase0=0.5, psi=1.5, inc=np.pi / 4, pdist=1.0,
                pphase=None, psrTerm=True, evolve=True, phase_approx=False,
                tref=53000 * 86400)

    out = np.zeros((3, 122))
    for i in range(3):
        out[i, :] = psrs[i].residuals.resids_value
    return out, psrs


def test_parity_with_libstempo_fixture(full_stack_residuals):
    residuals, _ = full_stack_residuals
    ref = np.load(FIXTURE)["residuals"]
    rms = np.sqrt(np.mean(residuals**2))
    dev = np.abs(residuals - ref) / rms
    assert dev.max() < 1e-3, f"max deviation {dev.max():.2e} of residual RMS"


def test_ledger_decomposition_sums_to_residuals(full_stack_residuals):
    """The provenance ledger decomposes total residuals by cause."""
    residuals, psrs = full_stack_residuals
    for i, psr in enumerate(psrs):
        total = np.sum(list(psr.added_signals_time.values()), axis=0)
        w = 1.0 / psr.toas.errors_s**2
        expect = total - np.sum(w * total) / np.sum(w)
        assert np.allclose(residuals[i], expect, atol=5e-9)
