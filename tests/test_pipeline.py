"""Pipelined sweep executor: byte-identical checkpoints at every depth,
crash-injection resume, bounded in-flight window, drain deadline, and
the sweep edge cases the executor must preserve (resume across a mesh
change, reduce_fn=None full cubes, _fn_id stability)."""
import glob
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import importlib

# the package attribute `utils.sweep` (the function) shadows the
# submodule on plain attribute import; resolve the MODULE explicitly
sweep_mod = importlib.import_module("pta_replicator_tpu.utils.sweep")
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.parallel.pipeline import DrainTimeout, run_pipelined
from pta_replicator_tpu.utils.sweep import _fn_id, sweep


@pytest.fixture()
def small_sweep():
    b = synthetic_batch(npsr=3, ntoa=64, seed=2)
    recipe = Recipe(
        efac=jnp.ones(3),
        rn_log10_amplitude=jnp.full(3, -14.0),
        rn_gamma=jnp.full(3, 4.0),
    )
    return b, recipe, jax.random.PRNGKey(5)


# ------------------------------------------------------------- executor

def test_run_pipelined_orders_and_bounds():
    """Writes happen strictly in index order; the in-flight window never
    exceeds depth; stats account every chunk."""
    written = []
    inflight = [0]
    peak = [0]
    lock = threading.Lock()

    def dispatch(i):
        with lock:
            inflight[0] += 1
            peak[0] = max(peak[0], inflight[0])
        return i

    def fetch(v):
        time.sleep(0.01)  # make the dispatcher run ahead
        with lock:
            inflight[0] -= 1
        return np.asarray([v])

    stats = run_pipelined(
        range(12), dispatch, lambda i, b: written.append(i),
        depth=3, fetch=fetch, drain_timeout_s=30.0,
    )
    assert written == list(range(12))
    assert stats["chunks"] == 12
    assert peak[0] <= 3
    assert stats["max_inflight"] <= 3


def test_run_pipelined_depth1_rejected():
    with pytest.raises(ValueError, match="depth"):
        run_pipelined(range(2), lambda i: i, lambda i, b: None, depth=1)


def test_run_pipelined_propagates_stage_exceptions_unchanged():
    class Boom(Exception):
        pass

    def bad_write(i, block):
        if i == 2:
            raise Boom("write failed")

    with pytest.raises(Boom):
        run_pipelined(
            range(6), lambda i: i, bad_write,
            depth=2, fetch=lambda v: np.asarray([v]),
        )

    def bad_dispatch(i):
        if i == 1:
            raise Boom("dispatch failed")
        return i

    with pytest.raises(Boom):
        run_pipelined(
            range(6), bad_dispatch, lambda i, b: None,
            depth=2, fetch=lambda v: np.asarray([v]),
        )


def test_run_pipelined_drain_timeout():
    """A wedged fetch (hung tunnel) raises DrainTimeout fast instead of
    blocking the sweep forever."""
    hang = threading.Event()

    def fetch(v):
        hang.wait(20.0)  # never set: simulated wedge
        return np.asarray([v])

    t0 = time.monotonic()
    with pytest.raises(DrainTimeout):
        run_pipelined(
            range(4), lambda i: i, lambda i, b: None,
            depth=2, fetch=fetch, drain_timeout_s=0.4,
        )
    assert time.monotonic() - t0 < 10.0
    hang.set()  # release the daemon thread


def test_run_pipelined_write_timeout():
    """A wedged checkpoint WRITE (hung filesystem) trips the same
    deadline as a wedged readback — the io_q back-pressure must not
    turn a dead mount into an unbounded hang."""
    hang = threading.Event()

    def write(i, block):
        hang.wait(20.0)  # never set: simulated dead mount

    t0 = time.monotonic()
    with pytest.raises(DrainTimeout):
        run_pipelined(
            range(6), lambda i: i, write,
            depth=2, fetch=lambda v: np.asarray([v]),
            drain_timeout_s=0.4,
        )
    assert time.monotonic() - t0 < 10.0
    hang.set()


# ------------------------------------------------- sweep byte-identity

def test_pipelined_sweep_checkpoints_byte_identical(tmp_path, small_sweep):
    """Depth 2 and depth 4 sweeps produce consolidated checkpoints (and
    meta sidecars) byte-for-byte equal to the synchronous depth-1 loop."""
    b, recipe, key = small_sweep
    paths = {}
    results = {}
    for depth in (1, 2, 4):
        ck = str(tmp_path / f"d{depth}.npz")
        results[depth] = sweep(
            key, b, recipe, nreal=32, chunk=4, checkpoint_path=ck,
            pipeline_depth=depth,
        )
        paths[depth] = ck
    ref_npz = open(paths[1], "rb").read()
    ref_meta = open(paths[1] + ".meta.json", "rb").read()
    for depth in (2, 4):
        assert open(paths[depth], "rb").read() == ref_npz
        assert open(paths[depth] + ".meta.json", "rb").read() == ref_meta
        np.testing.assert_array_equal(results[depth], results[1])
        # chunk files consolidated away at every depth
        assert glob.glob(paths[depth] + ".chunk*") == []


def test_pipelined_sweep_durable_writes_identical(tmp_path, small_sweep):
    """durable=True (fsync-backed writes) changes durability only, never
    file contents."""
    b, recipe, key = small_sweep
    ck1 = str(tmp_path / "plain.npz")
    ck2 = str(tmp_path / "durable.npz")
    sweep(key, b, recipe, nreal=8, chunk=4, checkpoint_path=ck1)
    sweep(key, b, recipe, nreal=8, chunk=4, checkpoint_path=ck2,
          durable=True)
    assert open(ck1, "rb").read() == open(ck2, "rb").read()


# ---------------------------------------------------- crash injection

class _KillSim(BaseException):
    """Out-of-band 'process died here' marker (BaseException so no
    library except-Exception handler can swallow it — like SIGKILL)."""


def _bomb_atomic_write(monkeypatch, nth_sidecar: int):
    """Kill the sweep between chunk-file write and sidecar write number
    ``nth_sidecar`` (1-based) — the exact window the crash-safety
    ordering exists for."""
    orig = sweep_mod._atomic_write
    seen = {"json": 0}

    def bombed(write_fn, final_path, suffix, durable=False):
        if suffix == ".json":
            seen["json"] += 1
            if seen["json"] == nth_sidecar:
                raise _KillSim()
        return orig(write_fn, final_path, suffix, durable=durable)

    monkeypatch.setattr(sweep_mod, "_atomic_write", bombed)


def test_crash_between_chunk_and_sidecar_resumes(
    tmp_path, small_sweep, monkeypatch
):
    """Kill after chunk 2's file landed but before its sidecar: resume
    must recompute ONLY chunks 2..end and still match the uninterrupted
    run byte-for-byte."""
    b, recipe, key = small_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ref_ck)

    ck = str(tmp_path / "crash.npz")
    _bomb_atomic_write(monkeypatch, nth_sidecar=3)  # chunk index 2's sidecar
    with pytest.raises(_KillSim):
        sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck,
              pipeline_depth=2)
    monkeypatch.undo()

    # the crash window: chunk 2's file exists, its sidecar says done=2
    assert os.path.exists(ck + ".chunk000002.npy")
    calls = []
    out = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck,
                pipeline_depth=2, progress=lambda d, t: calls.append(d))
    assert calls == [3, 4]  # chunks 0,1 NOT recomputed
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_crash_with_chunks_in_flight_resumes(
    tmp_path, small_sweep, monkeypatch
):
    """Kill at the FIRST sidecar of a depth-4 sweep — several chunks are
    dispatched/drained but unrecorded. Resume recomputes every chunk
    whose sidecar never landed and matches the reference bitwise."""
    b, recipe, key = small_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=32, chunk=4, checkpoint_path=ref_ck)

    ck = str(tmp_path / "crash.npz")
    _bomb_atomic_write(monkeypatch, nth_sidecar=2)
    with pytest.raises(_KillSim):
        sweep(key, b, recipe, nreal=32, chunk=4, checkpoint_path=ck,
              pipeline_depth=4)
    monkeypatch.undo()

    calls = []
    out = sweep(key, b, recipe, nreal=32, chunk=4, checkpoint_path=ck,
                pipeline_depth=4, progress=lambda d, t: calls.append(d))
    assert calls == list(range(2, 9))  # chunk 0 survived; 1..7 recomputed
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


# ------------------------------------------------- sweep edge cases

def test_stale_partial_archive_reaped(tmp_path, small_sweep):
    """A SIGKILLed pipelined sweep orphans `<ckpt>.partial` (the rename
    into place never ran); the next sweep over the same checkpoint must
    reuse/remove it rather than leak full-size archives per kill."""
    b, recipe, key = small_sweep
    ck = str(tmp_path / "s.npz")
    open(ck + ".partial", "wb").write(b"stale-partial-from-a-killed-run")
    out = sweep(key, b, recipe, nreal=8, chunk=4, checkpoint_path=ck,
                pipeline_depth=2)
    assert out.shape == (8, 3)
    assert not os.path.exists(ck + ".partial")
    # the finished checkpoint is intact (not the stale bytes)
    with np.load(ck) as z:
        assert set(z.files) == {"chunk0", "chunk1"}


def test_sweep_resume_after_mesh_change(tmp_path, small_sweep):
    """A sweep started without a mesh resumes on a 2-device mesh (the
    preemption case: a new slice rarely matches the old topology). The
    fingerprint deliberately excludes the mesh, and on a collective-free
    recipe the cross-topology resume stays bit-identical."""
    from pta_replicator_tpu.parallel import make_mesh

    b, recipe, key = small_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ref_ck)

    ck = str(tmp_path / "mesh.npz")

    class Stop(Exception):
        pass

    def bomb(done, total):
        if done == 2:
            raise Stop

    with pytest.raises(Stop):
        sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck,
              progress=bomb)
    mesh = make_mesh(2, 1)
    calls = []
    out = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck,
                mesh=mesh, progress=lambda d, t: calls.append(d))
    assert calls == [3, 4]
    np.testing.assert_array_equal(out, ref)


def test_sweep_reduce_none_full_cube(tmp_path, small_sweep):
    """reduce_fn=None keeps full (chunk, Np, Nt) residual cubes; the
    pipelined path must preserve the layout, order, and resume."""
    b, recipe, key = small_sweep
    ck1 = str(tmp_path / "cube1.npz")
    ck2 = str(tmp_path / "cube2.npz")
    full = sweep(key, b, recipe, nreal=8, chunk=4, checkpoint_path=ck1,
                 reduce_fn=None, pipeline_depth=1)
    piped = sweep(key, b, recipe, nreal=8, chunk=4, checkpoint_path=ck2,
                  reduce_fn=None, pipeline_depth=2)
    assert full.shape == (8, 3, 64)
    np.testing.assert_array_equal(piped, full)
    assert open(ck1, "rb").read() == open(ck2, "rb").read()
    with np.load(ck1) as z:
        assert set(z.files) == {"chunk0", "chunk1"}
        assert z["chunk0"].shape == (4, 3, 64)


def test_sweep_chunk_summary_reduce_matches_sync(tmp_path, small_sweep):
    """A reduce_fn that collapses the realization axis (per-chunk
    keepdims summary) must produce the same result at every depth: the
    pipelined path falls back to list+concatenate instead of broadcast-
    assigning into a (nreal, ...) preallocation."""
    import jax.numpy as jnp

    b, recipe, key = small_sweep

    def summarize(res, batch):
        return jnp.mean(res, axis=0, keepdims=True)  # (1, Np, Nt) / chunk

    ck1 = str(tmp_path / "sum1.npz")
    ck2 = str(tmp_path / "sum2.npz")
    sync = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck1,
                 reduce_fn=summarize, pipeline_depth=1)
    piped = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck2,
                  reduce_fn=summarize, pipeline_depth=2)
    assert sync.shape == (4, 3, 64)  # one row per CHUNK, not per real
    np.testing.assert_array_equal(piped, sync)
    assert open(ck1, "rb").read() == open(ck2, "rb").read()


def test_fn_id_stable_for_device_array_closures():
    """_fn_id hashes closure-captured device arrays by VALUE: equal
    arrays -> equal ids (across separately constructed closures and
    repeated calls), different values -> different ids. Guards the
    resume fingerprint against id()/repr() instability across process
    restarts."""
    w1 = jnp.asarray([1.0, 2.0, 3.0])
    w2 = jnp.asarray([1.0, 2.0, 3.0])
    w3 = jnp.asarray([1.0, 2.0, 4.0])

    mk = lambda w: (lambda res, batch: res * w)  # noqa: E731
    a, b, c = mk(w1), mk(w2), mk(w3)
    assert _fn_id(a) == _fn_id(a)  # stable across calls
    assert _fn_id(a) == _fn_id(b)  # value-equal captures
    assert _fn_id(a) != _fn_id(c)  # different captured values
    assert _fn_id(None) is None
