"""Population pipeline (Becsy+2022 outlier/free-spec split) and cosmology."""
import numpy as np
import pytest

from pta_replicator_tpu.utils.cosmology import (
    MPC_CM,
    MSOL_G,
    chirp_mass,
    comoving_distance_cm,
    gw_strain_source,
    luminosity_distance_cm,
    m1m2_from_mtmr,
)
from pta_replicator_tpu.models.population import (
    add_gwb_plus_outlier_cws,
    split_population,
)


def test_comoving_distance_vs_quad():
    """Fixed-order quadrature matches adaptive integration."""
    from scipy.integrate import quad
    from pta_replicator_tpu.utils.cosmology import _efunc, _H0_INV_CM

    for z in (0.1, 0.5, 1.0, 3.0, 6.0):
        expected = _H0_INV_CM * quad(lambda zz: 1.0 / _efunc(zz), 0, z)[0]
        np.testing.assert_allclose(comoving_distance_cm(z), expected, rtol=1e-10)
    # sanity scale: z=1 comoving distance ~ 3.4 Gpc for Planck15
    assert 3.3e3 < comoving_distance_cm(1.0) / MPC_CM < 3.5e3


def test_mass_utils_roundtrip():
    m1, m2 = m1m2_from_mtmr(10.0, 0.25)
    assert m1 + m2 == pytest.approx(10.0)
    assert m2 / m1 == pytest.approx(0.25)
    # equal-mass chirp mass: (m/2 * m/2)^0.6 / m^0.2 = m / 2^1.2
    assert chirp_mass(5.0, 5.0) == pytest.approx(10.0 / 2**1.2)


def test_strain_scalings():
    """h_s scales as Mc^(5/3), f^(2/3), 1/d."""
    h = gw_strain_source(1e9 * MSOL_G, 1e3 * MPC_CM, 1e-8)
    assert gw_strain_source(2e9 * MSOL_G, 1e3 * MPC_CM, 1e-8) == pytest.approx(h * 2 ** (5 / 3))
    assert gw_strain_source(1e9 * MSOL_G, 2e3 * MPC_CM, 1e-8) == pytest.approx(h / 2)
    assert gw_strain_source(1e9 * MSOL_G, 1e3 * MPC_CM, 2e-8) == pytest.approx(h * 2 ** (2 / 3))
    assert 1e-17 < h < 1e-13  # plausible PTA-band strain


def _toy_population(n=50, seed=0):
    rng = np.random.default_rng(seed)
    mtot = 10 ** rng.uniform(8.5, 10.0, n) * MSOL_G
    mrat = rng.uniform(0.2, 1.0, n)
    redz = rng.uniform(0.05, 2.0, n)
    fobs_gw = 10 ** rng.uniform(-8.9, -7.6, n)
    weights = rng.integers(1, 50, n).astype(float)
    return [mtot, mrat, redz, fobs_gw], weights


def test_split_population_conservation():
    vals, weights = _toy_population()
    fobs = np.logspace(-9, -7.5, 6)
    T = 16 * 365.25 * 86400.0
    split = split_population(vals, weights, fobs, T, outlier_per_bin=3)
    # per-bin: outliers + free-spec together carry all the weighted h^2
    in_band = (vals[3] >= fobs[0]) & (vals[3] < fobs[-1])
    assert split.outlier_fo.size <= 3 * (len(fobs) - 1)
    assert np.all(np.diff(np.sort(split.outlier_hs)) >= 0)
    # loudest-per-bin: every outlier louder than the free-spec residual mean
    assert split.user_spectrum.shape == (5, 2)
    # masses converted to observer frame Msol, distances to Mpc
    assert np.all((split.outlier_mc > 1e7) & (split.outlier_mc < 1e11))
    assert np.all((split.outlier_dl > 10) & (split.outlier_dl < 5e5))


def test_oracle_population_injection(psrs_small):
    vals, weights = _toy_population(30)
    fobs = np.logspace(-8.8, -7.8, 4)
    T = 10 * 365.25 * 86400.0
    out = add_gwb_plus_outlier_cws(
        psrs_small, vals, weights, fobs, T, outlier_per_bin=2, seed=99
    )
    assert len(out) == 11
    for psr in psrs_small:
        assert f"{psr.name}_gwb" in psr.added_signals
        assert f"{psr.name}_cw_catalog" in psr.added_signals
        res = psr.residuals.resids_value
        assert np.all(np.isfinite(res)) and res.std() > 0


def test_population_recipe_device(psrs_small):
    import jax
    from pta_replicator_tpu.batch import freeze
    from pta_replicator_tpu.models.batched import realize
    from pta_replicator_tpu.models.population import population_recipe
    from pta_replicator_tpu.ops.orf import hellings_downs_matrix
    from pta_replicator_tpu.ops.coords import pulsar_ra_dec

    b = freeze(psrs_small)
    locs = np.array(
        [
            (lambda rd: (rd[0], np.pi / 2 - rd[1]))(pulsar_ra_dec(p.loc, p.name))
            for p in psrs_small
        ]
    )
    vals, weights = _toy_population(30)
    fobs = np.logspace(-8.8, -7.8, 4)
    recipe = population_recipe(
        vals, weights, fobs, 10 * 365.25 * 86400.0,
        np.linalg.cholesky(hellings_downs_matrix(locs)),
        outlier_per_bin=2, gwb_npts=120, howml=4.0,
    )
    res = realize(jax.random.PRNGKey(0), b, recipe, nreal=3)
    assert res.shape == (3, 3, 122)
    assert bool(np.all(np.isfinite(np.asarray(res))))
