"""Regression tests for review findings on the oracle layer."""
import numpy as np
import pytest

from pta_replicator_tpu import add_red_noise, load_pulsar, make_ideal
from pta_replicator_tpu.io import read_tim

PAR = "/root/reference/test_partim_small/par/JPSR00.par"
TIM = "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim"


def test_fit_persists_to_par(tmp_path):
    """write_partim after fit() must write the fitted spin parameters."""
    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)
    t = (psr.toas.get_mjds() - psr.model.pepoch_mjd) * 86400.0
    psr.inject("spin_error", {}, 2e-13 * t)
    psr.fit(fitter="wls")
    psr.write_partim(str(tmp_path / "o.par"), str(tmp_path / "o.tim"))
    reloaded = load_pulsar(str(tmp_path / "o.par"), str(tmp_path / "o.tim"))
    assert reloaded.model.f0 == psr.model.f0
    assert reloaded.model.f1 == psr.model.f1
    # reloading the fitted pair reproduces small residuals
    assert np.sqrt(np.mean(reloaded.residuals.resids_value ** 2)) < 1e-8


def test_red_noise_explicit_modes():
    """Explicit mode frequencies are honored (draws sized to the modes)."""
    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)
    modes = np.arange(1, 11) / 1e8
    add_red_noise(psr, -14, 4.33, components=30, modes=modes, seed=7)
    dt = psr.added_signals_time[f"{psr.name}_red_noise"]
    assert dt.shape == (psr.toas.ntoas,)
    # delays reconstructable from the declared modes: same seed, same draws
    np.random.seed(7)
    eps = np.random.randn(2 * len(modes))
    from pta_replicator_tpu.models.red_noise import red_noise_delay

    # rebuild on pre-injection TOAs: undo the injected delay
    t_s = psr.toas.get_mjds() * 86400.0 - dt
    expect = red_noise_delay(
        t_s, -14, 4.33, eps, tspan_s=float(t_s.max() - t_s.min()), modes=modes
    )
    assert np.allclose(dt, expect, rtol=1e-6, atol=1e-12)


def test_tim_skip_blocks(tmp_path):
    """SKIP ... NOSKIP sections are excluded from the TOA set."""
    src = open(TIM).read().splitlines()
    # wrap two TOA lines in a SKIP block
    out = src[:2] + ["SKIP"] + src[2:4] + ["NOSKIP"] + src[4:]
    p = tmp_path / "skip.tim"
    p.write_text("\n".join(out) + "\n")
    toas = read_tim(str(p))
    full = read_tim(TIM)
    assert toas.ntoas == full.ntoas - 2


def test_tim_include_time_efac_equad(tmp_path):
    """INCLUDE pulls TOAs from other files; TIME/EFAC/EQUAD commands apply."""
    child = tmp_path / "child.tim"
    child.write_text(
        "FORMAT 1\n a 1440.0 53000.0 1.00000 AXIS\n a 1440.0 53010.0 1.00000 AXIS\n"
    )
    master = tmp_path / "master.tim"
    master.write_text(
        "FORMAT 1\nTIME 2.0\nEFAC 3.0\nEQUAD 4.0\nINCLUDE child.tim\n"
        " b 1440.0 53020.0 2.00000 AXIS\n"
    )
    toas = read_tim(str(master))
    assert toas.ntoas == 3
    # TIME offset: +2 s on every TOA
    assert abs(float((toas.mjd[0] - 53000.0) * 86400) - 2.0) < 1e-6
    # errors: hypot(efac * err, equad) in us
    assert toas.errors_s[0] == pytest.approx(np.hypot(3.0, 4.0) * 1e-6)
    assert toas.errors_s[2] == pytest.approx(np.hypot(6.0, 4.0) * 1e-6)


def test_assemble_orf_clm_length_validated():
    from pta_replicator_tpu.ops.orf import assemble_orf

    locs = np.array([[0.3, 1.0], [2.0, 2.0]])
    with pytest.raises(ValueError, match="coefficients"):
        assemble_orf(locs, clm=[1.0, 0.5], lmax=2)


def test_noise_dict_path_and_defaults(tmp_path):
    import json
    import pathlib
    from pta_replicator_tpu.io import parse_noise_dict

    p = tmp_path / "nd.json"
    p.write_text(json.dumps({"J0613-0200_430_ASP_efac": 1.1}))
    nd = parse_noise_dict(pathlib.Path(p))
    entry = nd["J0613-0200"]
    assert entry["backends"] == ["430_ASP"]
    assert entry["red_noise_gamma"] is None  # promised key, even if absent


def test_flag_tail_negative_values(tmp_path):
    """Negative numeric flag values are values, not new flag keys —
    including the '-inf'/'-nan' float spellings."""
    p = tmp_path / "neg.tim"
    p.write_text(
        "FORMAT 1\n a 1440.0 53000.0 0.5 AXIS -padd -1.5e-6 -be GUPPI\n"
        " a 1440.0 53001.0 0.5 AXIS -padd -inf -nu -nan -be GUPPI\n"
    )
    toas = read_tim(str(p))
    assert toas.flags[0] == {"padd": "-1.5e-6", "be": "GUPPI"}
    assert toas.flags[1] == {"padd": "-inf", "nu": "-nan", "be": "GUPPI"}


def test_user_spectrum_recipe_injects_gwb():
    """A Recipe with only a user spectrum (no power-law amplitude) injects."""
    import jax
    import jax.numpy as jnp
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models.batched import Recipe, realize

    b = synthetic_batch(npsr=3, ntoa=64, seed=4)
    spec = np.column_stack([np.logspace(-9.2, -7.4, 12), np.full(12, 1e-14)])
    recipe = Recipe(
        gwb_user_spectrum=jnp.asarray(spec),
        orf_cholesky=jnp.asarray(np.sqrt(2.0) * np.eye(3)),
        gwb_npts=100,
        gwb_howml=4.0,
    )
    res = realize(jax.random.PRNGKey(0), b, recipe, nreal=4)
    assert bool(np.all(np.isfinite(np.asarray(res))))
    assert float(np.std(np.asarray(res))) > 0


def test_measurement_noise_flag_validation():
    from pta_replicator_tpu import add_measurement_noise

    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)
    with pytest.raises(ValueError, match="must be scalars"):
        add_measurement_noise(psr, efac=[1.0, 1.1])
    with pytest.raises(ValueError, match="same length"):
        add_measurement_noise(psr, efac=[1.0, 1.1, 1.2], flags=["A", "B"])


def test_equad_convention_variances():
    """t2equad (default): EFAC scales (sigma and) EQUAD; tnequad: EQUAD
    adds unscaled (reference white_noise.py:64-76)."""
    from pta_replicator_tpu.models.white_noise import measurement_noise_delay

    rng = np.random.default_rng(0)
    n = 200_000
    err = np.full(n, 1e-7)
    ef, eq = np.full(n, 2.0), np.full(n, 3e-7)
    e1, e2 = rng.standard_normal(n), rng.standard_normal(n)
    t2 = measurement_noise_delay(err, ef, eq, e1, e2, tnequad=False)
    tn = measurement_noise_delay(err, ef, eq, e1, e2, tnequad=True)
    assert np.var(t2) == pytest.approx(4 * (1e-14 + 9e-14), rel=0.02)
    assert np.var(tn) == pytest.approx(4e-14 + 9e-14, rel=0.02)


def test_gwb_turnover_and_no_correlations():
    """Turnover suppresses hc below f0; no_correlations skips the ORF mix
    (reference red_noise.py:200-201, 246-252)."""
    from pta_replicator_tpu.models.gwb import characteristic_strain
    from pta_replicator_tpu import add_gwb

    f = np.logspace(-9.5, -7.5, 50)
    plain = characteristic_strain(f, -14.0, 13.0 / 3.0)
    turn = characteristic_strain(f, -14.0, 13.0 / 3.0, turnover=True,
                                 f0=1e-8, beta=1.0, power=2.0)
    lo, hi = f < 3e-9, f > 3e-8
    assert np.all(turn[lo] < 0.5 * plain[lo])   # suppressed below f0
    np.testing.assert_allclose(turn[hi], plain[hi], rtol=0.3)

    psrs = [load_pulsar(PAR, TIM)]
    make_ideal(psrs[0])
    add_gwb(psrs, -14.0, 4.33, no_correlations=True, seed=11, npts=100,
            howml=4)
    dt = psrs[0].added_signals_time[f"{psrs[0].name}_gwb"]
    assert dt.shape == (psrs[0].toas.ntoas,) and np.std(dt) > 0


def test_split_population_drops_zero_weight_outliers():
    from pta_replicator_tpu.models.population import split_population
    from pta_replicator_tpu.utils.cosmology import MSOL_G

    n = 10
    vals = [np.full(n, 1e9 * MSOL_G), np.full(n, 0.5), np.full(n, 0.5),
            np.full(n, 3e-9 + 1e-12 * np.arange(n))]
    weights = np.zeros(n)
    weights[3] = 5.0  # only one physical entry
    fobs = np.array([1e-9, 1e-8])
    split = split_population(vals, weights, fobs, 1e8, outlier_per_bin=4)
    assert split.outlier_fo.size == 1  # zero-weight entries filtered


def test_cw_catalog_vector_pdist_pphase_chunked():
    """Per-source pdist/pphase vectors must be sliced with the source
    chunks (review finding: unsliced vectors broadcast-crashed — or worse,
    misaligned — for catalogs larger than one chunk)."""
    from pta_replicator_tpu.models.cgw import add_catalog_of_cws

    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)
    n = 50
    rng = np.random.default_rng(1)
    cat = dict(
        gwtheta_list=np.arccos(rng.uniform(-1, 1, n)),
        gwphi_list=rng.uniform(0, 2 * np.pi, n),
        mc_list=10 ** rng.uniform(8, 9.4, n),
        dist_list=rng.uniform(10, 500, n),
        fgw_list=10 ** rng.uniform(-8.8, -7.6, n),
        phase0_list=rng.uniform(0, 2 * np.pi, n),
        psi_list=rng.uniform(0, np.pi, n),
        inc_list=np.arccos(rng.uniform(-1, 1, n)),
    )
    for kw in (
        dict(pdist=rng.uniform(0.4, 3.0, n)),
        dict(pphase=rng.uniform(0, 2 * np.pi, n)),
    ):
        name = next(iter(kw))
        add_catalog_of_cws(psr, **cat, **kw, chunk_size=7,
                           signal_name=f"{name}_chunked")
        add_catalog_of_cws(psr, **cat, **kw, chunk_size=10**6,
                           signal_name=f"{name}_whole")
        np.testing.assert_allclose(
            psr.added_signals_time[f"{psr.name}_{name}_chunked"],
            psr.added_signals_time[f"{psr.name}_{name}_whole"],
            rtol=1e-9,
        )


def test_static_delays_uses_f64_host_planes():
    """parallel.static_delays must keep the CW catalog's f64 host plane
    precompute: computing deterministic_delays with batch/recipe as *jit
    arguments* turns the source parameters into tracers and silently
    demotes the epoch-folded planes to ambient f32 (~1e-1 relative error
    on chirp phases). Guards the once-per-sweep static precompute path
    (bench.py, utils.sweep, parallel.static_delays) against that trap.
    """
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models.batched import Recipe, deterministic_delays
    from pta_replicator_tpu.parallel import static_delays

    rng = np.random.default_rng(0)
    n = 8
    cat = np.stack(
        [
            np.arccos(rng.uniform(-1, 1, n)),
            rng.uniform(0, 2 * np.pi, n),
            10 ** rng.uniform(8, 9.5, n),
            rng.uniform(50, 1000, n),
            10 ** rng.uniform(-8.8, -7.6, n),
            rng.uniform(0, 2 * np.pi, n),
            rng.uniform(0, np.pi, n),
            np.arccos(rng.uniform(-1, 1, n)),
        ]
    )

    def build(dtype):
        batch = synthetic_batch(npsr=4, ntoa=128, nbackend=2, seed=0, dtype=dtype)
        recipe = Recipe(cgw_params=jnp.asarray(cat, dtype), cgw_chunk=8)
        return batch, recipe

    b64, r64 = build(jnp.float64)
    oracle = np.asarray(deterministic_delays(b64, r64))
    rms = np.sqrt(np.mean(oracle**2))

    b32, r32 = build(jnp.float32)
    static = np.asarray(static_delays(b32, r32))
    rel = np.sqrt(np.mean((static - oracle) ** 2)) / rms
    assert rel < 1e-3, rel

    # the trap this test exists for: the same computation through a jit
    # boundary loses the host precompute and lands far outside the f32
    # floor — if this ever starts passing at 1e-3, the traced path has
    # been fixed and static_delays may be simplified
    traced = np.asarray(jax.jit(deterministic_delays)(b32, r32))
    rel_traced = np.sqrt(np.mean((traced - oracle) ** 2)) / rms
    assert rel_traced > 10 * rel, (rel_traced, rel)


def test_user_spectrum_floor_warns():
    """Strain entries below the 1e-30 interpolation floor must warn (the
    reference interpolates raw values, red_noise.py:255-263 — silent
    flooring was a behavioral divergence)."""
    import warnings as _w
    from pta_replicator_tpu.models.gwb import characteristic_strain

    f = np.logspace(-9, -8, 10)
    spec_low = np.column_stack([f, np.full(10, 1e-40)])
    with pytest.warns(UserWarning, match="floored to 1e-30"):
        hcf = characteristic_strain(f, user_spectrum=spec_low)
    assert np.all(hcf == pytest.approx(1e-30))

    spec_ok = np.column_stack([f, np.full(10, 1e-15)])
    with _w.catch_warnings():
        _w.simplefilter("error")
        characteristic_strain(f, user_spectrum=spec_ok)  # no warning


def test_chromatic_noise_gradient_finite():
    """The freq<=0 where-branch must not poison gradients: an epsilon
    substitution makes the untaken (ref/eps)^idx branch inf at f32, and
    inf * 0 = NaN through the where in reverse mode."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B

    b = synthetic_batch(npsr=2, ntoa=32, seed=7)
    # force one barycentric (freq <= 0) TOA per pulsar
    freqs = np.asarray(b.freqs_mhz).copy()
    freqs[:, 0] = 0.0
    b = dataclasses.replace(b, freqs_mhz=jnp.asarray(freqs, b.toas_s.dtype))
    key = jax.random.PRNGKey(3)

    def total(log10_a):
        d = B.chromatic_noise_delays(
            key, b, log10_amplitude=log10_a, gamma=3.1, chromatic_index=2.0
        )
        return jnp.sum(d**2)

    g = jax.grad(total)(jnp.asarray(-13.5, b.toas_s.dtype))
    assert bool(jnp.isfinite(g))


def test_user_spectrum_loglog_flat_clamp():
    """Frequencies outside the user grid get the flat endpoint value —
    the reference's shipped extrap1d (red_noise.py:23-26: the slope
    continuation is commented out). The synthesis grid reaches ~howml
    (10x) below user grids where hc^2/f^3 dominates, so slope
    extrapolation there would inject very different GWB power."""
    from pta_replicator_tpu.models.gwb import characteristic_strain

    # hc ~ f^-2/3 power law sampled on an interior grid
    uf = np.logspace(-8.5, -7.5, 8)
    uh = 1e-15 * (uf / 1e-8) ** (-2.0 / 3.0)
    spec = np.column_stack([uf, uh])
    f = np.logspace(-9.5, -6.5, 40)  # extends a decade past both ends
    got = characteristic_strain(f, user_spectrum=spec)
    inside = (f >= uf[0]) & (f <= uf[-1])
    want = 1e-15 * (f / 1e-8) ** (-2.0 / 3.0)
    np.testing.assert_allclose(got[inside], want[inside], rtol=1e-10)
    np.testing.assert_allclose(got[f < uf[0]], uh[0], rtol=1e-10)
    np.testing.assert_allclose(got[f > uf[-1]], uh[-1], rtol=1e-10)
