"""Scenario layer (pta_replicator_tpu.scenarios): spec validation,
compiler determinism and seed discipline, the batched-vs-oracle
differential, the shrinker, the sweep provenance stamp, the Recipe
early-validation satellite, the scenario lint rule, and the CLI.

CPU-only and fixture-free (everything runs on synthetic batches).
"""
import json
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.scenarios import (
    ScenarioSpec,
    SpecError,
    compile_spec,
    flagship_workload,
    load_spec,
    spec_families,
)
from pta_replicator_tpu.scenarios import fuzz as fz

BASE = {
    "name": "t", "seed": 3,
    "array": {"npsr": 3, "ntoa": 64, "nbackend": 2, "span_days": 2000.0},
    "white": {"efac": 1.1, "per_backend": True},
    "red": {"log10_amplitude": -14.0, "gamma": 3.0, "nmodes": 4},
}


def mkspec(**over):
    d = {**{k: (dict(v) if isinstance(v, dict) else v)
            for k, v in BASE.items()}, **over}
    return ScenarioSpec.from_dict(d)


# -------------------------------------------------------- spec validation

def test_spec_rejects_unknown_key_naming_field():
    with pytest.raises(SpecError, match="array.*unknown key.*npulsars"):
        mkspec(array={"npulsars": 3}).validate()
    with pytest.raises(SpecError, match="unknown top-level"):
        ScenarioSpec.from_dict({"array": {}, "whtie": {}})


def test_spec_rejects_bad_distribution_grammar():
    with pytest.raises(SpecError, match="white.efac.dist"):
        mkspec(white={"efac": {"dist": "zipf", "lo": 1}}).validate()
    with pytest.raises(SpecError, match="lo must be <= hi"):
        mkspec(white={"efac": {"dist": "uniform", "lo": 2.0,
                               "hi": 1.0}}).validate()
    with pytest.raises(SpecError, match="needs 'sd'"):
        mkspec(white={"efac": {"dist": "normal", "mean": 1.0}}).validate()


def test_spec_rejects_inconsistent_sections():
    with pytest.raises(SpecError, match="population and gwb"):
        mkspec(
            gwb={"log10_amplitude": -14.0, "gamma": 4.0},
            population={"n_binaries": 10},
        ).validate()
    with pytest.raises(SpecError, match="transient.psr.*out of range"):
        mkspec(transient={"psr": 7, "log10_amp": -7.0}).validate()
    with pytest.raises(SpecError, match="nreal.*multiple"):
        mkspec(sweep={"nreal": 5, "chunk": 2}).validate()
    with pytest.raises(SpecError, match="no signal family"):
        ScenarioSpec.from_dict({"array": {"npsr": 2}}).validate()


def test_spec_version_and_preset_guards():
    with pytest.raises(SpecError, match="newer than this reader"):
        mkspec(scenario_version=99).validate()
    with pytest.raises(SpecError, match="preset.*must not also carry"):
        ScenarioSpec.from_dict({
            "preset": "bench_flagship", "white": {"efac": 1.0},
        }).validate()
    with pytest.raises(SpecError, match="preset must be one of"):
        ScenarioSpec.from_dict({"preset": "nope"}).validate()


# ---------------------------------------------- round-trip + determinism

def test_spec_roundtrip_identical_hash_and_compile(tmp_path):
    spec = mkspec(
        gwb={"log10_amplitude": {"dist": "uniform", "lo": -14.5,
                                 "hi": -14.0},
             "gamma": 4.33, "npts": 64, "howml": 4.0, "orf": "none"},
    ).validate()
    path = str(tmp_path / "s.json")
    spec.save(path)
    back = load_spec(path)
    assert back.content_hash == spec.content_hash
    c1, c2 = compile_spec(spec), compile_spec(back)
    assert c1.spec_hash == c2.spec_hash
    for f, v in vars(c1.recipe).items():
        v2 = getattr(c2.recipe, f)
        if v is not None and hasattr(v, "shape"):
            np.testing.assert_array_equal(np.asarray(v), np.asarray(v2))
    np.testing.assert_array_equal(
        np.asarray(c1.batch.toas_s), np.asarray(c2.batch.toas_s)
    )


def test_compile_deterministic_across_process_restarts(tmp_path):
    """The same spec must compile to byte-identical draws in a FRESH
    process (the committed-spec stability contract)."""
    spec = mkspec(seed=17)
    path = str(tmp_path / "s.json")
    spec.validate().save(path)
    prog = (
        "import json,hashlib,numpy as np;"
        "from pta_replicator_tpu.scenarios import load_spec, compile_spec;"
        f"c = compile_spec(load_spec({path!r}));"
        "h = hashlib.sha256();"
        "[h.update(np.ascontiguousarray(np.asarray(v)).tobytes())"
        " for f, v in sorted(vars(c.recipe).items())"
        " if v is not None and hasattr(v, 'shape')];"
        "print(h.hexdigest())"
    )
    outs = {
        subprocess.run(
            [sys.executable, "-c", prog], capture_output=True,
            text=True, check=True,
            env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
        ).stdout.strip()
        for _ in range(2)
    }
    assert len(outs) == 1, outs


def test_fold_in_family_independence():
    """Dropping one section must leave every other family's compiled
    draws bit-identical — the property the shrinker stands on."""
    with_burst = mkspec(burst={"log10_amp": -7.0}).validate()
    without = mkspec().validate()
    c1, c2 = compile_spec(with_burst), compile_spec(without)
    for f in ("efac", "rn_log10_amplitude", "rn_gamma"):
        np.testing.assert_array_equal(
            np.asarray(getattr(c1.recipe, f)),
            np.asarray(getattr(c2.recipe, f)),
        )
    np.testing.assert_array_equal(
        np.asarray(c1.batch.toas_s), np.asarray(c2.batch.toas_s)
    )


def test_flagship_spec_matches_bench_fingerprint():
    """The committed flagship spec and bench.build_workload are the same
    workload: equal content fingerprints (the /tmp/workload.npz cache
    contract) at a reduced size, through both entry points."""
    import bench

    small = dict(npsr=4, ntoa=128, nbackend=2, ncw=3)
    _, _, fp_shim = bench.build_workload(**small, with_fingerprint=True)
    _, _, fp_direct = flagship_workload(**small, with_fingerprint=True)
    assert fp_shim == fp_direct
    spec = ScenarioSpec.from_dict({
        "name": "flagship", "preset": "bench_flagship",
        "preset_params": small,
    }).validate()
    assert compile_spec(spec).fingerprint == fp_direct


# -------------------------------------------------- differential + shrink

def test_differential_agrees_on_mixed_scenario():
    spec = mkspec(
        ecorr={"log10_ecorr": -6.8},
        gwb={"log10_amplitude": -14.3, "gamma": 4.33, "npts": 64,
             "howml": 4.0, "orf": "hd"},
        cw={"nsrc": 2},
        memory={"log10_strain": -13.0},
        transient={"psr": 1, "kind": "glitch", "log10_amp": -6.5},
    ).validate()
    res = fz.run_scenario(compile_spec(spec))
    assert res.agree, res.to_dict()
    assert set(res.verdicts) == {
        "white", "ecorr", "red", "gwb", "cw", "memory", "transient",
        "total",
    }
    for fam, v in res.verdicts.items():
        assert v["rel"] <= v["tol"], (fam, v)


def test_planted_disagreement_detected_and_shrunk(tmp_path):
    spec = mkspec(
        ecorr={"log10_ecorr": -6.8},
        burst={"log10_amp": -7.0},
    ).validate()
    perturb = {"family": "ecorr", "scale": 1.01}
    res = fz.run_scenario(compile_spec(spec), perturb=perturb)
    assert not res.agree
    assert res.worst_family == "ecorr"

    def fails(s):
        return not fz.run_scenario(compile_spec(s, validate=False),
                                   perturb=perturb).agree

    minimal, steps = fz.shrink(spec, fails)
    assert steps > 0
    assert spec_families(minimal) == ("ecorr",)
    # replayable, and innocent without the planted defect
    path = str(tmp_path / "min.json")
    minimal.save(path)
    assert fz.run_scenario(compile_spec(load_spec(path))).agree


def test_generator_deterministic_and_positionally_independent():
    a = fz.sample_spec(9, 4)
    b = fz.sample_spec(9, 4)
    assert a.to_dict() == b.to_dict()
    assert a.content_hash == fz.sample_spec(9, 4).content_hash
    # scenario 4 is the same spec no matter how many others ran
    assert fz.sample_spec(9, 5).content_hash != a.content_hash


# ------------------------------------------------------ sweep provenance

def test_sweep_provenance_stamped_and_fingerprinted(tmp_path):
    from pta_replicator_tpu.utils.sweep import sweep

    spec = mkspec(sweep={"nreal": 4, "chunk": 2}).validate()
    c = compile_spec(spec)
    ck = str(tmp_path / "ck.npz")
    out = sweep(c.realize_key(), c.batch, c.recipe, nreal=4,
                checkpoint_path=ck, chunk=2, reduce_fn=None,
                provenance=c.provenance())
    meta = json.load(open(ck + ".meta.json"))
    assert meta["provenance"]["spec_hash"] == c.spec_hash
    assert meta["provenance"]["spec_name"] == "t"
    # resume with the same stamp: instant, identical
    again = sweep(c.realize_key(), c.batch, c.recipe, nreal=4,
                  checkpoint_path=ck, chunk=2, reduce_fn=None,
                  provenance=c.provenance())
    np.testing.assert_array_equal(out, again)
    # a different stamp must refuse to resume
    with pytest.raises(ValueError, match="different sweep"):
        sweep(c.realize_key(), c.batch, c.recipe, nreal=4,
              checkpoint_path=ck, chunk=2, reduce_fn=None,
              provenance={"spec_name": "other", "spec_hash": "beef",
                          "scenario_version": 1})


# ------------------------------------- Recipe early-validation satellite

@pytest.mark.parametrize("kwargs,frag", [
    (dict(burst_sky=jnp.zeros(3)), "burst needs all of"),
    (dict(burst_hplus=jnp.zeros(8)), "burst needs all of"),
    (dict(transient_waveform=jnp.zeros(16)), "travel together"),
    (dict(transient_grid=jnp.zeros(2)), "travel together"),
    (dict(cgw_pdist=jnp.ones(3)), "set cgw_params too"),
    (dict(cgw_pphase=jnp.ones(3)), "set cgw_params too"),
    (dict(rn_log10_amplitude=jnp.asarray(-14.0)), "rn_gamma"),
    (dict(chrom_log10_amplitude=jnp.asarray(-14.0)), "chrom_gamma"),
    (dict(gwb_log10_amplitude=jnp.asarray(-14.0)), "gwb_gamma"),
    (dict(cgw_params=jnp.zeros((3, 8))), "(8, Ns)"),
    (dict(cgw_params=jnp.zeros((8, 3)), cgw_pdist=jnp.ones((2, 4))),
     "3 source"),
    (dict(cgw_params=jnp.zeros((8, 3)), cgw_pphase=jnp.ones(4)),
     "3 source"),
    (dict(gwm_params=jnp.zeros(4)), "gwm_params"),
    (dict(burst_sky=jnp.zeros(4), burst_hplus=jnp.zeros(8),
          burst_hcross=jnp.zeros(8), burst_grid=jnp.zeros(2)),
     "burst_sky"),
])
def test_recipe_rejects_inconsistent_combo(kwargs, frag):
    with pytest.raises(ValueError, match="Recipe"):
        try:
            Recipe(**kwargs)
        except ValueError as exc:
            assert frag in str(exc), str(exc)
            raise


def test_recipe_validation_survives_pytree_roundtrips():
    import jax

    r = Recipe(efac=jnp.ones(3), cgw_params=jnp.zeros((8, 2)),
               cgw_pdist=jnp.ones(2))
    # unflatten with placeholder leaves (structure probes) must not raise
    jax.tree_util.tree_map(lambda _: 0, r)
    # unflatten with tracers (jit) runs the shape checks and passes
    out = jax.jit(lambda rr: rr.efac * 2)(r)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    # valid user-spectrum-only GWB stays constructible
    Recipe(gwb_user_spectrum=jnp.ones((5, 2)))


# ----------------------------------------------------- scenario lint rule

def _lint_scenarios(tmp_path, body):
    import textwrap as tw

    from pta_replicator_tpu.analysis import engine as eng
    from pta_replicator_tpu.analysis import rules_scenarios

    rel = "pta_replicator_tpu/scenarios/zz_fixture.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(tw.dedent(body))
    mods, problems = eng.parse_modules([str(path)], str(tmp_path))
    active, suppressed = eng.run_rules(mods, rules_scenarios.RULES)
    return problems + active


def test_scenario_split_chain_fires_on_sequential_split(tmp_path):
    findings = _lint_scenarios(tmp_path, """
        import jax

        def chain(key, n):
            key, sub = jax.random.split(key)
            return sub
    """)
    assert [f.rule for f in findings] == ["scenario-split-chain"]
    assert "rebinds its own key operand" in findings[0].message


def test_scenario_split_chain_fires_on_draw_in_loop(tmp_path):
    findings = _lint_scenarios(tmp_path, """
        import jax

        def draws(root, n):
            out = []
            for i in range(n):
                out.append(jax.random.normal(root, (4,)))
            return out
    """)
    assert [f.rule for f in findings] == ["scenario-split-chain"]
    assert "fold_in" in findings[0].message


def test_scenario_split_chain_allows_fold_in_indexing(tmp_path):
    findings = _lint_scenarios(tmp_path, """
        import jax

        def keys(root, n):
            out = []
            for i in range(n):
                out.append(jax.random.fold_in(root, i))
            return out

        def family(root):
            k1, k2 = jax.random.split(root)
            return jax.random.normal(k1, (4,)) + jax.random.normal(
                k2, (4,))
    """)
    assert findings == []


def test_scenario_rule_scoped_to_scenarios_subtree(tmp_path):
    import textwrap as tw

    from pta_replicator_tpu.analysis import engine as eng
    from pta_replicator_tpu.analysis import rules_scenarios

    rel = "pta_replicator_tpu/models/zz_other.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(tw.dedent("""
        import jax

        def chain(key):
            key, sub = jax.random.split(key)
            return sub
    """))
    mods, problems = eng.parse_modules([str(path)], str(tmp_path))
    active, _ = eng.run_rules(mods, rules_scenarios.RULES)
    assert problems + active == []


# ------------------------------------------------------------------- CLI

def test_cli_scenario_validate_compile_replay(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    spec = mkspec(
        gwb={"log10_amplitude": -14.3, "gamma": 4.33, "npts": 64,
             "howml": 4.0, "orf": "none"},
        sweep={"nreal": 4, "chunk": 2},
    ).validate()
    path = str(tmp_path / "s.json")
    spec.save(path)

    main(["scenario", "validate", path])
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["valid"] and rec["hash"] == spec.content_hash

    out = str(tmp_path / "w.npz")
    main(["scenario", "compile", path, "--out", out])
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["fingerprint"] == spec.content_hash
    with np.load(out) as z:
        assert z["static"].shape == (3, 64)
        assert str(z["fingerprint"]) == spec.content_hash

    ck = str(tmp_path / "ck.npz")
    res = str(tmp_path / "r.npz")
    main(["scenario", "run", path, "--out", res, "--checkpoint", ck])
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["shape"] == [4, 3, 64]
    meta = json.load(open(ck + ".meta.json"))
    assert meta["provenance"]["spec_hash"] == spec.content_hash

    main(["scenario", "replay", path])
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["agree"] is True


def test_cli_scenario_validate_rejects_bad_spec(tmp_path):
    from pta_replicator_tpu.__main__ import main

    path = str(tmp_path / "bad.json")
    json.dump({"array": {"npsr": 3}, "white": {"efac": -2.0}},
              open(path, "w"))
    with pytest.raises(SystemExit, match="efac"):
        main(["scenario", "validate", path])


def test_spec_rejects_wrong_length_value_list():
    # explicit per-pulsar lists must match array.npsr AT VALIDATE TIME
    with pytest.raises(SpecError, match="white.efac.*array.npsr = 3"):
        mkspec(white={"efac": [1.0, 1.1]}).validate()
    with pytest.raises(SpecError, match="red.gamma.*array.npsr = 3"):
        mkspec(red={"log10_amplitude": -14.0,
                    "gamma": [3.0, 3.1]}).validate()
    # a flat list is ambiguous under per_backend
    with pytest.raises(SpecError, match="cannot combine with"):
        mkspec(white={"efac": [1.0, 1.1, 1.2],
                      "per_backend": True}).validate()
    # correct length passes and compiles
    c = compile_spec(mkspec(white={"efac": [1.0, 1.1, 1.2]}).validate())
    np.testing.assert_allclose(np.asarray(c.recipe.efac),
                               [1.0, 1.1, 1.2])


def test_preset_param_flows_into_recipe():
    spec = ScenarioSpec.from_dict({
        "preset": "bench_flagship",
        "preset_params": {"npsr": 4, "ntoa": 128, "nbackend": 2,
                          "ncw": 3, "cgw_backend": "pallas_interpret"},
    }).validate()
    assert compile_spec(spec).recipe.cgw_backend == "pallas_interpret"


def test_spec_rejects_misspelled_preset_param():
    with pytest.raises(SpecError, match="preset_params.*ncww"):
        ScenarioSpec.from_dict({
            "preset": "bench_flagship", "preset_params": {"ncww": 50},
        }).validate()


def test_cli_scenario_run_guards(tmp_path, capsys):
    from pta_replicator_tpu.__main__ import main

    spec = mkspec(sweep={"nreal": 4, "chunk": 2}).validate()
    path = str(tmp_path / "s.json")
    spec.save(path)
    # a --nreal the spec's chunk does not divide must be a named error,
    # not a deep sweep traceback (and never a silent chunk change —
    # chunking changes the fold_in key layout)
    with pytest.raises(SystemExit, match="multiple of the spec's"):
        main(["scenario", "run", path, "--nreal", "3",
              "--checkpoint", str(tmp_path / "ck.npz")])
    # nreal SMALLER than the spec chunk is the same silent-rechunk
    # hazard and must also be a named error
    with pytest.raises(SystemExit, match="multiple of the spec's"):
        main(["scenario", "run", path, "--nreal", "1",
              "--checkpoint", str(tmp_path / "ck2.npz")])
    # run takes exactly one spec; extras must not be silently dropped
    with pytest.raises(SystemExit, match="exactly one SPEC"):
        main(["scenario", "run", path, path])
    # compile --out with several specs would overwrite the output
    with pytest.raises(SystemExit, match="exactly one SPEC"):
        main(["scenario", "compile", path, path,
              "--out", str(tmp_path / "w.npz")])
