"""Temporal telemetry layer (obs/series.py + flightrec wiring):
bounded decimating rings, streaming percentiles, trends in the v3
heartbeat, series.jsonl persistence, and the telemetry-overhead
accounting. Fixture-free and CPU-only — part of the scripts/check.sh
pre-push subset."""
import json
import os
import random
import time

import numpy as np
import pytest

from pta_replicator_tpu import obs
from pta_replicator_tpu.obs import flightrec, names
from pta_replicator_tpu.obs.metrics import MetricsRegistry
from pta_replicator_tpu.obs.series import (
    P2Quantile,
    Ring,
    SeriesRecorder,
    load_series,
    quantiles_from_histogram,
)


def _load_checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    return checker


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


# ------------------------------------------------------------- rings

def test_ring_hammer_stays_within_budget():
    """10k samples into a 256-budget ring: the retained count (and the
    derived byte estimate) never exceeds the budget at ANY point, the
    stride is a power of two, and the retained history still spans the
    whole input range (decimation coarsens, it does not forget)."""
    ring = Ring(budget=256)
    for i in range(10_000):
        ring.offer(float(i), float(i))
        assert len(ring) <= 256
        assert ring.nbytes() <= 256 * Ring.SAMPLE_NBYTES
    assert ring.stride & (ring.stride - 1) == 0  # power of two
    ts = [t for t, _ in ring.samples]
    assert ts[0] == 0.0                  # oldest sample survives
    assert ts[-1] >= 10_000 - ring.stride  # newest within one stride
    assert ts == sorted(ts)


def test_ring_no_overflow_keeps_every_sample():
    ring = Ring(budget=64)
    for i in range(50):
        ring.offer(float(i), float(2 * i))
    assert len(ring) == 50 and ring.stride == 1
    assert ring.samples[7] == (7.0, 14.0)


def test_ring_rejects_tiny_budget():
    with pytest.raises(ValueError):
        Ring(budget=2)


# -------------------------------------------------------- percentiles

def test_p2_quantile_tracks_numpy():
    rng = random.Random(7)
    vals = [rng.gauss(10.0, 3.0) for _ in range(20_000)]
    for p in (0.5, 0.95, 0.99):
        est = P2Quantile(p)
        for v in vals:
            est.observe(v)
        true = float(np.percentile(vals, 100 * p))
        spread = float(np.std(vals))
        assert abs(est.value - true) < 0.1 * spread, (p, est.value, true)


def test_p2_quantile_small_counts_exact():
    est = P2Quantile(0.5)
    for v in (5.0, 1.0, 3.0):
        est.observe(v)
    assert est.value == 3.0
    assert P2Quantile(0.5).value is None


def test_quantiles_from_histogram_interpolates():
    # two buckets: 50 obs uniform in (0, 1], 50 in (1, 2]
    buckets = (1.0, 2.0)
    counts = [50, 50, 0]  # incl. +Inf tail
    qs = quantiles_from_histogram(buckets, counts)
    assert qs["p50"] == pytest.approx(1.0, abs=0.05)
    assert qs["p95"] == pytest.approx(1.9, abs=0.05)
    assert quantiles_from_histogram(buckets, [0, 0, 0]) == {}


# ----------------------------------------------------- series recorder

def test_recorder_samples_labeled_families_and_skips_optouts():
    reg = MetricsRegistry()
    rec = SeriesRecorder(reg)
    reg.gauge(names.OCCUPANCY_DUTY_CYCLE, stage="drain").set(0.8)
    reg.gauge(names.OCCUPANCY_DUTY_CYCLE, stage="io_write").set(0.2)
    reg.counter(names.CW_STREAM_BYTES_STAGED, device="0").inc(100)
    reg.counter(names.IO_TIM_TOAS).inc(5)  # io.* is not opted in
    rec.sample()
    flat = set()
    for (name, labels) in rec._rings:
        flat.add((name, tuple(labels)))
    assert (names.OCCUPANCY_DUTY_CYCLE, (("stage", "drain"),)) in flat
    assert (names.OCCUPANCY_DUTY_CYCLE, (("stage", "io_write"),)) in flat
    assert any(n == names.CW_STREAM_BYTES_STAGED for n, _ in flat)
    assert not any(n == names.IO_TIM_TOAS for n, _ in flat)


def test_recorder_byte_budget_under_hammer():
    """10k sampling ticks over several series: total retained bytes stay
    under the recorder's hard bound, and the per-series cap drops new
    series instead of growing without limit."""
    reg = MetricsRegistry()
    rec = SeriesRecorder(reg, ring_budget=64, max_series=8)
    g = reg.gauge(names.SWEEP_CHUNKS_DONE)
    for d in range(12):  # 12 labeled instances > max_series 8
        reg.counter(names.CW_STREAM_BYTES_STAGED, device=str(d)).inc()
    for i in range(10_000):
        g.set(i)
        rec.sample()
    bound = 8 * 64 * Ring.SAMPLE_NBYTES
    assert rec.nbytes() <= bound
    assert len(rec._rings) <= 8
    assert rec._dropped_series > 0
    for entry in rec._rings.values():
        assert len(entry["ring"]) <= 64


def test_recorder_trends_rate_and_direction():
    reg = MetricsRegistry()
    rec = SeriesRecorder(reg)
    c = reg.counter(names.SWEEP_CHUNKS_DONE)
    g = reg.gauge(names.SWEEP_INFLIGHT_CHUNKS)
    # synthesize rising counter + falling gauge by driving sample()
    for i in range(10):
        c.inc(5)
        g.set(100 - 10 * i)
        rec.sample()
        time.sleep(0.01)
    trends = rec.trends(window_s=60.0)
    up = trends[names.SWEEP_CHUNKS_DONE]
    down = trends[names.SWEEP_INFLIGHT_CHUNKS]
    assert up["rate_per_s"] > 0 and up["trend"] == "rising"
    assert down["rate_per_s"] < 0 and down["trend"] == "falling"
    assert up["latest"] == 50


def test_recorder_span_quantiles_bounded_names():
    rec = SeriesRecorder(MetricsRegistry())
    for i in range(rec.MAX_SPAN_NAMES + 10):
        rec.observe_span({"type": "span", "name": f"s{i}", "wall_s": 0.1})
    assert len(rec._span_q) == rec.MAX_SPAN_NAMES
    for _ in range(100):
        rec.observe_span({"type": "span", "name": "s0", "wall_s": 0.25})
    q = rec.span_quantiles()["s0"]
    assert q["count"] == 101
    assert q["p50"] == pytest.approx(0.25, rel=0.2)


def test_series_jsonl_roundtrip_and_schema(tmp_path):
    reg = MetricsRegistry()
    rec = SeriesRecorder(reg)
    g = reg.gauge(names.SWEEP_CHUNKS_DONE)
    reg.histogram(names.JAX_COMPILE_S).observe(0.5)
    for i in range(20):
        g.set(i)
        rec.sample()
        rec.observe_span({"type": "span", "name": "dispatch",
                          "wall_s": 0.01 * (i + 1)})
    path = str(tmp_path / "series.jsonl")
    rec.write_jsonl(path)
    doc = load_series(path)
    assert doc["meta"]["schema"] == 1
    by_name = {s["name"]: s for s in doc["series"]}
    assert len(by_name[names.SWEEP_CHUNKS_DONE]["samples"]) == 20
    # wall-clock stamps (comparable with span t0), oldest first
    ts = [t for t, _ in by_name[names.SWEEP_CHUNKS_DONE]["samples"]]
    assert ts == sorted(ts) and abs(ts[-1] - time.time()) < 60
    kinds = {(q["name"], q["kind"]) for q in doc["quantiles"]}
    assert ("dispatch", "span") in kinds
    assert (names.JAX_COMPILE_S, "histogram") in kinds
    # and the schema checker accepts the artifact
    checker = _load_checker()
    assert checker.validate_series_file(path) == []


def test_series_schema_checker_rejects_malformed(tmp_path):
    checker = _load_checker()
    p = tmp_path / "series.jsonl"
    p.write_text(json.dumps({"type": "series", "name": "x"}) + "\n")
    problems = checker.validate_series_file(str(p))
    assert any("missing" in x for x in problems)
    assert any("series_meta" in x for x in problems)
    p.write_text(
        json.dumps({"type": "series_meta", "schema": 1, "t0": 1.0,
                    "pid": 1}) + "\n"
        + json.dumps({"type": "series", "name": "x", "labels": {},
                      "kind": "gauge", "stride": 1,
                      "samples": [[1.0, "oops"]]}) + "\n"
    )
    problems = checker.validate_series_file(str(p))
    assert any("malformed sample" in x for x in problems)


# ------------------------------------------------ flightrec integration

def test_heartbeat_v3_has_trends_and_validates(tmp_path):
    d = str(tmp_path / "cap")
    obs.start_capture(d, heartbeat_interval_s=0.05, stall_timeout_s=None)
    try:
        for i in range(6):
            with obs.span(names.SPAN_DISPATCH, chunk=i):
                obs.gauge(names.SWEEP_CHUNKS_DONE).set(i)
            time.sleep(0.06)
    finally:
        obs.finish_capture()
    hb = json.loads((tmp_path / "cap" / "progress.json").read_text())
    assert hb["schema"] == flightrec.PROGRESS_SCHEMA_VERSION >= 3
    assert isinstance(hb["trends"], dict)
    assert names.SWEEP_CHUNKS_DONE in hb["trends"]
    assert "latest" in hb["trends"][names.SWEEP_CHUNKS_DONE]
    checker = _load_checker()
    assert checker.validate_flightrec_file(
        str(tmp_path / "cap" / "progress.json"), "progress") == []
    # the capture also leaves the series history + live artifacts
    assert checker.validate_series_file(
        str(tmp_path / "cap" / "series.jsonl")) == []
    assert (tmp_path / "cap" / "series.json").exists()
    assert (tmp_path / "cap" / "metrics.prom").exists()


def test_overhead_counter_accrues_and_stays_small(tmp_path):
    """The sampler self-accounts its tick CPU cost into obs.overhead_s;
    at a 50 ms cadence over ~0.6 s the counter must exist, be sampled
    as a series, and stay far below the wall. The lower bound is >= 0
    rather than > 0 on purpose: CLOCK_THREAD_CPUTIME_ID is ~10 ms
    granular on older kernels, so a dozen cheap ticks can legitimately
    read zero CPU (the <1%-of-step claim itself is measured over a
    30 s steady-state window by bench.py)."""
    d = str(tmp_path / "cap")
    t0 = time.monotonic()
    obs.start_capture(d, heartbeat_interval_s=0.05, stall_timeout_s=None)
    try:
        time.sleep(0.6)
    finally:
        obs.finish_capture()
    wall = time.monotonic() - t0
    metrics = json.loads((tmp_path / "cap" / "metrics.json").read_text())
    assert names.OBS_OVERHEAD_S in metrics  # the accounting is wired
    overhead = metrics[names.OBS_OVERHEAD_S][0]["value"]
    assert 0.0 <= overhead < 0.5 * wall
    # and it was itself sampled as a series
    series = load_series(str(tmp_path / "cap" / "series.jsonl"))
    assert any(s["name"] == names.OBS_OVERHEAD_S for s in series["series"])


def test_postmortem_flush_writes_series(tmp_path):
    d = str(tmp_path / "cap")
    os.makedirs(d)
    rec = flightrec.FlightRecorder(d, stall_timeout_s=None)
    rec.series.sample()
    rec.write_postmortem("test")
    assert os.path.exists(os.path.join(d, "series.jsonl"))
    checker = _load_checker()
    assert checker.validate_series_file(
        os.path.join(d, "series.jsonl")) == []


def test_report_renders_series_sections(tmp_path):
    d = str(tmp_path / "cap")
    obs.start_capture(d, heartbeat_interval_s=0.05, stall_timeout_s=None)
    try:
        for i in range(5):
            with obs.span(names.SPAN_DISPATCH, chunk=i):
                obs.gauge(names.SWEEP_CHUNKS_DONE).set(i)
            time.sleep(0.06)
    finally:
        obs.finish_capture()
    from pta_replicator_tpu.obs.report import render_report

    out = render_report(d)
    assert "series (sampled by the flight recorder):" in out
    assert names.SWEEP_CHUNKS_DONE in out
    assert "latency percentiles" in out
    assert "p95" in out
    as_json = json.loads(render_report(d, as_json=True))
    assert as_json["series"]["meta"]["schema"] == 1


def test_sparkline_shapes():
    from pta_replicator_tpu.obs.report import sparkline

    assert sparkline([]) == ""
    assert sparkline([1.0, 1.0, 1.0]) == "▁▁▁"
    s = sparkline(list(range(100)), width=16)
    assert len(s) == 16 and s[0] == "▁" and s[-1] == "█"


# -------------------------------------------------- bench-diff classes

def test_regress_directions_for_series_leaves():
    from pta_replicator_tpu.obs.regress import metric_direction

    assert metric_direction("dispatch.p95") is False
    assert metric_direction("telemetry.quantiles.io_write.p99") is False
    assert metric_direction("obs.overhead_s") is False
    assert metric_direction("obs_overhead.overhead_pct_of_step") is False
    assert metric_direction("trends.sweep.chunks_done.rate_per_s") is True
    # raw ring observations are info, never verdicts
    assert metric_direction("series.sweep.chunks_done.stride") is None
    assert metric_direction("series.dropped_series") is None
