"""Multi-device sharding tests on the 8-device virtual CPU mesh.

Validates the full TPU scale-out story without TPU hardware: the 2-D
('real', 'psr') mesh, sharded realization batches, and that sharding is a
pure layout choice (results identical to the single-device path up to
float reduction order in partitioned contractions).
"""
import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models import batched as B
from pta_replicator_tpu.ops.orf import hellings_downs_matrix
from pta_replicator_tpu.parallel import (
    make_mesh,
    sharded_realize,
    shardmap_realize,
)


def assert_shardmap_matches_realize(batch, recipe, key, mesh, nreal=8):
    """shardmap_realize over ``mesh`` must reproduce the single-device
    B.realize result (one tolerance policy for every engine test)."""
    ref = B.realize(key, batch, recipe, nreal=nreal, fit=True)
    out = shardmap_realize(
        key, batch, recipe, nreal=nreal, mesh=mesh, fit=True
    )
    rms = float(np.sqrt(np.mean(np.asarray(ref) ** 2)))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-9, atol=1e-9 * rms
    )


@pytest.fixture(scope="module")
def small_setup():
    batch = synthetic_batch(npsr=4, ntoa=64, nbackend=2, seed=1)
    phat = np.asarray(batch.phat)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]), np.arccos(phat[:, 2])], axis=1
    )  # (phi, theta)
    orf = hellings_downs_matrix(locs)
    recipe = B.Recipe(
        efac=jnp.ones((4, 2)),
        log10_equad=jnp.full((4, 2), -6.3),
        log10_ecorr=jnp.full((4, 2), -6.5),
        rn_log10_amplitude=jnp.full(4, -14.0),
        rn_gamma=jnp.full(4, 4.33),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        orf_cholesky=jnp.asarray(np.linalg.cholesky(np.asarray(orf))),
        gwb_npts=100,
        gwb_howml=4.0,
    )
    return batch, recipe


def test_mesh_shapes():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(4, 2)
    assert mesh.shape == {"real": 4, "psr": 2}
    # smaller meshes use a prefix of the devices; oversubscription raises
    assert make_mesh(3, 2).shape == {"real": 3, "psr": 2}
    with pytest.raises(ValueError, match="needs"):
        make_mesh(5, 2)


def test_sharded_matches_single_device(small_setup):
    batch, recipe = small_setup
    key = jax.random.PRNGKey(42)
    ref = B.realize(key, batch, recipe, nreal=8, fit=True)

    mesh = make_mesh(4, 2)
    out = sharded_realize(key, batch, recipe, nreal=8, mesh=mesh, fit=True)
    assert out.shape == (8, 4, 64)
    # sharding is layout only: same keys -> same numbers, up to float
    # reduction order in the partitioned contractions (GWB synthesis matmul)
    rms = float(np.sqrt(np.mean(np.asarray(ref) ** 2)))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-9, atol=1e-9 * rms
    )
    # output really is distributed over the mesh
    assert len(out.sharding.device_set) == 8


def test_realization_axis_only_mesh(small_setup):
    batch, recipe = small_setup
    mesh = make_mesh(8, 1)
    out = sharded_realize(jax.random.PRNGKey(0), batch, recipe, nreal=16, mesh=mesh)
    assert out.shape == (16, 4, 64)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_nreal_divisibility_error(small_setup):
    batch, recipe = small_setup
    mesh = make_mesh(4, 2)
    with pytest.raises(ValueError, match="divisible"):
        sharded_realize(jax.random.PRNGKey(0), batch, recipe, nreal=6, mesh=mesh)


@pytest.mark.parametrize("n_real,n_psr", [(8, 1), (4, 2)])
def test_shardmap_matches_constraint_path(small_setup, n_real, n_psr):
    """The explicit-SPMD shard_map engine produces the same realizations
    as the sharding-constraint engine — on a realization-only mesh AND
    with the pulsar axis sharded (GWB ORF rows + row-windowed draws)."""
    batch, recipe = small_setup
    key = jax.random.PRNGKey(9)
    mesh = make_mesh(n_real, n_psr)
    a = sharded_realize(key, batch, recipe, nreal=16, mesh=mesh, fit=True)
    b = shardmap_realize(key, batch, recipe, nreal=16, mesh=mesh, fit=True)
    rms = float(np.sqrt(np.mean(np.asarray(a) ** 2)))
    np.testing.assert_allclose(
        np.asarray(b), np.asarray(a), rtol=1e-9, atol=1e-9 * rms
    )


def test_shardmap_psr_sharded_with_cw_catalog(small_setup):
    """Deterministic CW catalog under a sharded pulsar axis: the scan
    carry must inherit the input's device-varying type (regression: a
    fresh jnp.zeros carry fails shard_map's scan vma check)."""
    batch, recipe = small_setup
    rng = np.random.default_rng(3)
    ncw = 6
    cat = jnp.asarray(np.stack([
        np.arccos(rng.uniform(-1, 1, ncw)), rng.uniform(0, 2 * np.pi, ncw),
        10 ** rng.uniform(8, 9.3, ncw), rng.uniform(50, 900, ncw),
        10 ** rng.uniform(-8.6, -7.8, ncw), rng.uniform(0, 2 * np.pi, ncw),
        rng.uniform(0, np.pi, ncw), np.arccos(rng.uniform(-1, 1, ncw)),
    ]))
    recipe = dataclasses.replace(recipe, cgw_params=cat, cgw_chunk=4)
    assert_shardmap_matches_realize(
        batch, recipe, jax.random.PRNGKey(21), make_mesh(4, 2)
    )


def test_shardmap_psr_sharded_uncorrelated_gwb(small_setup):
    """With no ORF (uncorrelated common process) the psr-sharded engine
    materializes the global sqrt(2)*I factor so shards draw distinct
    rows; result matches the single-device path."""
    batch, recipe = small_setup
    recipe = dataclasses.replace(recipe, orf_cholesky=None)
    assert_shardmap_matches_realize(
        batch, recipe, jax.random.PRNGKey(11), make_mesh(4, 2)
    )


def test_distributed_helpers(small_setup):
    """Single-process topology, per-host key folding, and local-shard
    materialization of a globally-sharded realization array."""
    from pta_replicator_tpu.parallel import distributed

    topo = distributed.initialize()
    assert topo["process_count"] == 1 and topo["process_index"] == 0
    assert topo["global_device_count"] == 8

    k0 = distributed.process_key(jax.random.PRNGKey(3), 0)
    k1 = distributed.process_key(jax.random.PRNGKey(3), 1)
    assert not np.array_equal(np.asarray(k0), np.asarray(k1))

    batch, recipe = small_setup
    mesh = make_mesh(8, 1)
    out = sharded_realize(jax.random.PRNGKey(1), batch, recipe, nreal=16, mesh=mesh)
    local = distributed.local_realizations(out)
    # single host: local view is the whole array, in realization order
    np.testing.assert_array_equal(local, np.asarray(out))

    # pulsar-sharded mesh: psr shards of one realization block must be
    # stitched along the pulsar axis, not stacked as extra realizations
    mesh2 = make_mesh(4, 2)
    out2 = sharded_realize(jax.random.PRNGKey(1), batch, recipe, nreal=8, mesh=mesh2)
    local2 = distributed.local_realizations(out2)
    np.testing.assert_array_equal(local2, np.asarray(out2))

    with pytest.raises((RuntimeError, ValueError)):
        distributed.initialize(
            coordinator_address="localhost:1", num_processes=4, process_id=0
        )


def test_anisotropic_gwb_device_correlations(small_setup):
    """Device-path GWB with an anisotropic (lmax=1) ORF recovers that ORF
    in realization-averaged cross-correlations."""
    from pta_replicator_tpu.ops.orf import assemble_orf

    batch, recipe = small_setup
    phat = np.asarray(batch.phat)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]), np.arccos(phat[:, 2])], axis=1
    )
    clm = np.array([np.sqrt(4 * np.pi), 0.4, 0.3, -0.2])
    orf = assemble_orf(locs, clm=clm, lmax=1)
    M = np.linalg.cholesky(orf)
    keys = jax.random.split(jax.random.PRNGKey(5), 1200)
    d = jax.vmap(
        lambda k: B.gwb_delays(k, batch, -14.0, 4.33, M, npts=150, howml=4)
    )(keys)
    d = np.asarray(d)
    cov = np.einsum("ran,rbn->ab", d, d) / (d.shape[0] * d.shape[2])
    corr = cov / np.sqrt(np.outer(np.diag(cov), np.diag(cov)))
    expect = orf / np.sqrt(np.outer(np.diag(orf), np.diag(orf)))
    np.testing.assert_allclose(corr, expect, atol=0.1)


def test_shardmap_psr_sharded_guards(small_setup):
    """Loud failures for the psr-sharded engine's unsupported inputs:
    a global-pulsar-index transient, npsr not divisible by the axis, and
    a per-pulsar recipe leaf with the wrong leading dim."""
    batch, recipe = small_setup
    key = jax.random.PRNGKey(0)
    mesh = make_mesh(2, 2)

    r_tr = dataclasses.replace(
        recipe,
        transient_waveform=jnp.zeros(16),
        transient_grid=jnp.asarray([0.0, 1.0e7]),
        transient_psr=2,
    )
    with pytest.raises(ValueError, match="transient"):
        shardmap_realize(key, batch, r_tr, nreal=8, mesh=mesh)

    b3 = synthetic_batch(npsr=3, ntoa=32, nbackend=2, seed=2)
    r3 = dataclasses.replace(
        recipe,
        efac=jnp.ones(3),
        log10_equad=jnp.full(3, -6.3),
        log10_ecorr=jnp.full(3, -6.5),
        rn_log10_amplitude=jnp.full(3, -14.0),
        rn_gamma=jnp.full(3, 4.33),
        orf_cholesky=jnp.eye(3),
    )
    with pytest.raises(ValueError, match="divisible"):
        shardmap_realize(key, b3, r3, nreal=8, mesh=mesh)

    r_bad = dataclasses.replace(recipe, efac=jnp.ones(6))
    with pytest.raises(ValueError, match="leading dim"):
        shardmap_realize(key, batch, r_bad, nreal=8, mesh=mesh)


def test_shardmap_psr_sharded_with_design_fit(small_setup):
    """The per-realization full-design refit (Recipe.fit_design) works
    under a sharded pulsar axis: the (Np, Nt, K) tensor shards its rows
    and the per-pulsar solves stay local."""
    batch, recipe = small_setup
    rng = np.random.default_rng(5)
    D = jnp.asarray(rng.normal(size=(batch.npsr, batch.ntoa_max, 5)))
    recipe = dataclasses.replace(recipe, fit_design=D)
    assert_shardmap_matches_realize(
        batch, recipe, jax.random.PRNGKey(31), make_mesh(4, 2)
    )


@pytest.mark.parametrize("n_real,n_psr", [(8, 1), (4, 2)])
def test_engines_accept_precomputed_static(small_setup, n_real, n_psr):
    """sharded_realize/shardmap_realize with a precomputed static_delays
    array must match their compute-internally default (the once-per-sweep
    hoist used by utils.sweep and bench.py), including on a pulsar-sharded
    mesh where the static delays shard along 'psr'."""
    from pta_replicator_tpu.parallel import static_delays

    batch, recipe = small_setup
    rng = np.random.default_rng(5)
    ncw = 6
    cat = jnp.asarray(np.stack([
        np.arccos(rng.uniform(-1, 1, ncw)), rng.uniform(0, 2 * np.pi, ncw),
        10 ** rng.uniform(8, 9.3, ncw), rng.uniform(50, 900, ncw),
        10 ** rng.uniform(-8.6, -7.8, ncw), rng.uniform(0, 2 * np.pi, ncw),
        rng.uniform(0, np.pi, ncw), np.arccos(rng.uniform(-1, 1, ncw)),
    ]))
    recipe = dataclasses.replace(recipe, cgw_params=cat, cgw_chunk=4)
    mesh = make_mesh(n_real, n_psr)
    key = jax.random.PRNGKey(33)
    static = static_delays(batch, recipe, mesh=mesh)
    assert np.asarray(jnp.abs(static)).max() > 0  # CW delays are nonzero

    for engine in (sharded_realize, shardmap_realize):
        ref = engine(key, batch, recipe, nreal=8, mesh=mesh, fit=True)
        out = engine(
            key, batch, recipe, nreal=8, mesh=mesh, fit=True, static=static
        )
        rms = float(np.sqrt(np.mean(np.asarray(ref) ** 2)))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-9, atol=1e-7 * rms
        )


def test_gls_fit_through_sharded_engines():
    """Recipe.fit_gls (nested-Woodbury GLS design fit) runs through both
    mesh engines, incl. a sharded pulsar axis, matching the
    single-device path."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.parallel import (
        make_mesh,
        shardmap_realize,
        sharded_realize,
    )

    batch = synthetic_batch(npsr=4, ntoa=96, nbackend=2, seed=3)
    rng = np.random.default_rng(2)
    # a small synthetic design: constant, linear, and a backend indicator
    t = np.asarray(batch.toas_s)
    D = np.stack([
        np.ones_like(t),
        t / np.asarray(batch.tspan_s)[:, None],
        np.asarray(batch.backend_index == 1, dtype=np.float64),
    ], axis=-1)
    recipe = B.Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.3, (4, 2))),
        log10_ecorr=jnp.asarray(rng.uniform(-6.8, -6.4, (4, 2))),
        rn_log10_amplitude=jnp.full(4, -13.6),
        rn_gamma=jnp.full(4, 3.8),
        fit_design=jnp.asarray(D),
        fit_gls=True,
    )
    key = jax.random.PRNGKey(11)
    ref = np.asarray(B.realize(key, batch, recipe, nreal=8, fit=True))
    for mesh in (make_mesh(8, 1), make_mesh(4, 2)):
        a = np.asarray(sharded_realize(
            key, batch, recipe, nreal=8, mesh=mesh, fit=True))
        b = np.asarray(shardmap_realize(
            key, batch, recipe, nreal=8, mesh=mesh, fit=True))
        rms = float(np.sqrt(np.mean(ref**2)))
        assert np.max(np.abs(a - ref)) < 1e-8 * rms
        assert np.max(np.abs(b - ref)) < 1e-8 * rms
