import os

import numpy as np
import pytest

from pta_replicator_tpu import (
    SimulatedPulsar,
    load_from_directories,
    load_pulsar,
    make_ideal,
    simulate_pulsar,
)


def test_load_from_directories(partim_small):
    pardir, timdir = partim_small
    psrs = load_from_directories(pardir, timdir, num_psrs=3)
    assert [p.name for p in psrs] == ["JPSR00", "JPSR01", "JPSR02"]
    assert all(p.toas.ntoas == 122 for p in psrs)


def test_make_ideal_zeroes_residuals(partim_small):
    pardir, timdir = partim_small
    psr = load_pulsar(pardir + "/JPSR00.par", timdir + "/fake_JPSR00_noiseonly.tim")
    make_ideal(psr)
    assert psr.added_signals == {}
    # residuals at sub-ns level after the fixed point
    assert np.max(np.abs(psr.residuals.resids_value)) < 1e-9


def test_inject_requires_make_ideal(partim_small):
    pardir, timdir = partim_small
    psr = load_pulsar(pardir + "/JPSR00.par", timdir + "/fake_JPSR00_noiseonly.tim")
    with pytest.raises(ValueError, match="make_ideal"):
        psr.update_added_signals("x", {})


@pytest.fixture()
def fabricated_psr(tmp_path):
    """A make_ideal'd pulsar with no reference-fixture dependency."""
    par = tmp_path / "fake.par"
    par.write_text(
        "PSR JFAKE00\nRAJ 04:37:15.8\nDECJ -47:15:08.6\n"
        "F0 173.6879458121843\nF1 -1.728e-15\nPEPOCH 53000\nDM 2.64\n"
    )
    psr = simulate_pulsar(
        str(par), np.linspace(53000.0, 53600.0, 50), 0.5
    )
    make_ideal(psr)
    return psr


def test_duplicate_signal_disambiguated(fabricated_psr):
    """Repeated injections under one name get deterministic suffixes
    (name, name_2, name_3, ...) and keep separate ledger entries."""
    psr = fabricated_psr
    assert psr.update_added_signals("sig", {"a": 1}) == "sig"
    assert psr.update_added_signals("sig", {"a": 2}) == "sig_2"
    assert psr.update_added_signals("sig", {"a": 3}) == "sig_3"
    assert psr.added_signals["sig"] == {"a": 1}
    assert psr.added_signals["sig_2"] == {
        "a": 2, "disambiguated_from": "sig"
    }
    # the delay ledger stays per-entry too
    n = psr.toas.ntoas
    psr.inject("dup", {}, np.full(n, 1e-7))
    assert psr.inject("dup", {}, np.full(n, 2e-7)) == "dup_2"
    assert np.allclose(psr.added_signals_time["dup"], 1e-7)
    assert np.allclose(psr.added_signals_time["dup_2"], 2e-7)


def test_injected_delay_appears_in_residuals(psrs_small):
    psr = psrs_small[0]
    rng = np.random.default_rng(0)
    dt = rng.normal(scale=1e-6, size=psr.toas.ntoas)
    psr.inject("test_sig", {}, dt)
    # residuals = injected delay minus its weighted mean (equal errors -> mean)
    expect = dt - dt.mean()
    # phase-based residuals at longdouble precision carry ~0.1 ns noise
    assert np.allclose(psr.residuals.resids_value, expect, atol=3e-9)
    # ledger carries the raw delay vector
    assert np.allclose(psr.added_signals_time["test_sig"], dt)


def test_simulate_pulsar(partim_small):
    pardir, _ = partim_small
    mjds = np.arange(53000, 54000, 30.0)
    psr = simulate_pulsar(pardir + "/JPSR00.par", mjds, toaerr=1.0)
    assert psr.toas.ntoas == len(mjds)
    make_ideal(psr)
    assert np.max(np.abs(psr.residuals.resids_value)) < 1e-9


def test_fit_removes_quadratic(psrs_small):
    psr = psrs_small[0]
    t = (psr.toas.get_mjds() - psr.model.pepoch_mjd) * 86400.0
    dt = 3e-13 * t + 1e-21 * t**2  # mimic an F0/F1 offset (max ~100 us)
    psr.inject("spin_error", {}, dt)
    pre_rms = float(np.sqrt(np.mean(psr.residuals.resids_value ** 2)))
    psr.fit(fitter="wls")
    post_rms = float(np.sqrt(np.mean(psr.residuals.resids_value ** 2)))
    assert post_rms < pre_rms * 1e-3


def test_write_partim_roundtrip(tmp_path, psrs_small):
    psr = psrs_small[0]
    psr.inject("sig", {}, np.full(psr.toas.ntoas, 1e-6))
    psr.write_partim(str(tmp_path / "o.par"), str(tmp_path / "o.tim"))
    reloaded = load_pulsar(str(tmp_path / "o.par"), str(tmp_path / "o.tim"))
    assert reloaded.name == psr.name
    assert np.max(np.abs((reloaded.toas.mjd - psr.toas.mjd).astype(float))) < 1e-14


def test_real_nanograv_pulsar_end_to_end(tmp_path):
    """Realistic workload: the 7,758-TOA NANOGrav B1855+09 (ecliptic
    coordinates, binary/DM terms in the par) loads, idealizes to sub-ns,
    injects, and round-trips with every par parameter preserved."""
    par = "/root/reference/test_partim/par/B1855+09.par"
    tim = "/root/reference/test_partim/tim/B1855+09.tim"
    if not (os.path.isfile(par) and os.path.isfile(tim)):
        pytest.skip("reference NANOGrav fixture not available")
    from pta_replicator_tpu import (
        add_measurement_noise,
        load_pulsar,
        make_ideal,
    )

    psr = load_pulsar(par, tim)
    assert psr.toas.ntoas == 7758
    assert set(psr.loc) == {"ELONG", "ELAT"}  # ecliptic loc extraction
    make_ideal(psr)
    assert float(np.sqrt(np.mean(psr.residuals.resids_value ** 2))) < 1e-9
    add_measurement_noise(psr, efac=1.1, seed=5)
    rms = float(np.sqrt(np.mean(psr.residuals.resids_value ** 2)))
    assert rms > 1e-7  # real ~us TOA errors scaled by efac

    psr.write_partim(str(tmp_path / "o.par"), str(tmp_path / "o.tim"))
    orig = {l.split()[0] for l in open(par) if l.split()}
    new = {l.split()[0] for l in open(tmp_path / "o.par") if l.split()}
    assert orig <= new  # binary/DM/astrometry params ride along unmodified


def test_to_enterprise_optional_dependency(partim_small):
    """C8: to_enterprise converts through a written par/tim pair when
    `enterprise` is importable; otherwise it raises ImportError naming
    the manual equivalent (NOT NotImplementedError — the export is
    implemented, the dependency is optional)."""
    pardir, timdir = partim_small
    psr = load_pulsar(
        pardir + "/JPSR00.par", timdir + "/fake_JPSR00_noiseonly.tim"
    )
    make_ideal(psr)
    try:
        import enterprise.pulsar  # noqa: F401

        have_enterprise = True
    except ImportError:
        have_enterprise = False

    if not have_enterprise:
        with pytest.raises(ImportError, match="write_partim"):
            psr.to_enterprise()
        return

    ent = psr.to_enterprise()
    assert ent.toas.shape == (psr.toas.ntoas,)
    np.testing.assert_allclose(
        np.sort(ent.toas) / 86400.0,
        np.sort(psr.toas.get_mjds()),
        rtol=0,
        atol=1e-7,  # enterprise returns SSB-corrected days*86400
    )


def test_load_from_directories_parallel_matches_serial(partim_small):
    """Threaded ingest returns the same pulsars in the same order as the
    serial loop (the C tim tokenizer releases the GIL, so workers>1
    overlaps file scans)."""
    pardir, timdir = partim_small
    serial = load_from_directories(pardir, timdir, workers=1)
    threaded = load_from_directories(pardir, timdir, workers=3)
    assert [p.name for p in threaded] == [p.name for p in serial]
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(
            np.asarray(a.toas.mjd, float), np.asarray(b.toas.mjd, float)
        )
        np.testing.assert_array_equal(a.toas.errors_s, b.toas.errors_s)


def test_to_enterprise_success_path_executes(monkeypatch, tmp_path):
    """C8: execute to_enterprise's SUCCESS path (enterprise itself is not
    installable in this image) by stubbing ``enterprise.pulsar.Pulsar``
    with a loader that consumes the exact surface enterprise does — the
    freshly written par/tim pair, read back inside the constructor while
    the tempdir still exists. Structurally validates that the pair
    round-trips through this framework's own loader with flags, JUMPs,
    and DMX intact (B1855+09: 442 DMX lines, 1 flag-matched JUMP)."""
    import sys
    import types

    par = "/root/reference/test_partim/par/B1855+09.par"
    tim = "/root/reference/test_partim/tim/B1855+09.tim"
    if not (os.path.exists(par) and os.path.exists(tim)):
        pytest.skip("large B1855+09 fixture absent")
    psr = load_pulsar(par, tim)
    make_ideal(psr)

    captured = {}

    class _StubPulsar:
        def __init__(self, parfile, timfile, ephem=None,
                     timing_package=None, **kw):
            # load while the TemporaryDirectory is still alive — exactly
            # when enterprise's own constructor would parse the files
            reloaded = load_pulsar(parfile, timfile)
            captured["psr"] = reloaded
            captured["ephem"] = ephem
            captured["timing_package"] = timing_package

    mod = types.ModuleType("enterprise")
    sub = types.ModuleType("enterprise.pulsar")
    sub.Pulsar = _StubPulsar
    mod.pulsar = sub
    monkeypatch.setitem(sys.modules, "enterprise", mod)
    monkeypatch.setitem(sys.modules, "enterprise.pulsar", sub)

    out = psr.to_enterprise(ephem="DE440", timing_package="pint")
    assert isinstance(out, _StubPulsar)
    assert captured["ephem"] == "DE440"
    back = captured["psr"]

    # the surface enterprise consumes: epochs, errors, flags, model pars
    assert back.toas.ntoas == psr.toas.ntoas
    dmjd_s = np.abs(
        (back.toas.mjd - psr.toas.mjd).astype(np.float64)) * 86400.0
    assert dmjd_s.max() < 1e-9
    np.testing.assert_allclose(
        back.toas.errors_s, psr.toas.errors_s, rtol=1e-9)
    assert back.toas.flags[0] == psr.toas.flags[0]  # -fe/-be backend flags

    # DMX windows and the flag-matched JUMP must survive the round-trip
    assert any(k.startswith("DMX_") for k in back.par.params), "DMX lost"
    assert "JUMP" in open(par).read()
    assert back.par.jumps, "flag-matched JUMP lost on round-trip"
    assert len(back.par.jumps) == len(psr.par.jumps)
