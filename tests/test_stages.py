"""Stage-graph executor property suite (parallel/stages.py) + the
fused-sweep byte-identity ladder.

The generic executor's contracts — FIFO ordering, bounded windows,
DrainTimeout, exception-in-order, stop/drain, fault sites, trace
adoption across every thread hop, stats/occupancy — are pinned here
directly on declared graphs; the ported executors' own pinned behavior
stays in tests/test_pipeline.py / test_cw_stream.py / test_multichip.py
/ test_faults.py (all of which now run through this machinery). The
fused sweep (utils/sweep.py fused_stream=True) is pinned byte-identical
to the stacked path at depths 1/2/4 including crash-resume and
supervised fault recovery."""
import glob
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu import obs
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.faults import inject
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.obs import names
from pta_replicator_tpu.obs.trace import TRACER, chunk_trace_context
from pta_replicator_tpu.parallel.stages import (
    DrainTimeout,
    Stage,
    StageGraph,
    fan_out,
)
from pta_replicator_tpu.utils.sweep import sweep


def _passthrough(i, payload, sp):
    return payload


# ---------------------------------------------------------- driver mode

def test_run_orders_bounds_and_stats():
    """FIFO end to end, window never exceeded, stats account every
    item with the full key set."""
    written = []
    inflight = [0]
    peak = [0]
    lock = threading.Lock()

    def produce(i, _p, sp):
        with lock:
            inflight[0] += 1
            peak[0] = max(peak[0], inflight[0])
        return i

    def transform(i, v, sp):
        time.sleep(0.005)  # let the source run ahead into the window
        with lock:
            inflight[0] -= 1
        return v * 10

    g = StageGraph(
        [
            Stage("produce", fn=produce),
            Stage("transform", fn=transform, releases_window=True,
                  out_maxsize=3),
            Stage("sink", fn=lambda i, v, sp: written.append((i, v))),
        ],
        window=3,
        drain_timeout_s=30.0,
    )
    stats = g.run(range(10))
    assert written == [(i, i * 10) for i in range(10)]
    assert peak[0] <= 3
    assert stats["items"] == 10
    assert stats["max_inflight"] <= 3
    assert set(stats) >= {
        "items", "wall_s", "max_inflight", "window_wait_s", "stall_s",
        "stage_busy_s", "occupancy",
    }
    assert set(stats["stage_busy_s"]) == {"produce", "transform", "sink"}
    assert stats["occupancy"].get("bottleneck")


def test_run_inline_is_synchronous():
    """Single-thread placement: every stage runs on the caller's
    thread, strictly interleaved per item — the depth-1 sweep shape."""
    events = []
    main = threading.get_ident()

    def a(i, _p, sp):
        events.append(("a", i, threading.get_ident()))
        return i

    def b(i, v, sp):
        events.append(("b", i, threading.get_ident()))

    StageGraph(
        [
            Stage("a", fn=a, placement="inline"),
            Stage("b", fn=b, placement="inline"),
        ],
    ).run(range(3))
    assert [(s, i) for s, i, _t in events] == [
        ("a", 0), ("b", 0), ("a", 1), ("b", 1), ("a", 2), ("b", 2)
    ]
    assert all(t == main for _s, _i, t in events)


def test_run_exception_unchanged_and_marked():
    """A stage exception re-raises UNCHANGED on the driver, with the
    failing item index attached via mark_item (the sweep's
    supervised-recovery contract)."""

    class Boom(Exception):
        pass

    marks = []

    def mark(exc, i):
        marks.append(i)

    def bad(i, v, sp):
        if i == 2:
            raise Boom("stage failed")
        return v

    with pytest.raises(Boom, match="stage failed"):
        StageGraph(
            [
                Stage("src", fn=_passthrough),
                Stage("bad", fn=bad, releases_window=True),
                Stage("sink", fn=lambda i, v, sp: None),
            ],
            window=2,
            mark_item=mark,
        ).run(range(6))
    assert 2 in marks


def test_run_drain_timeout_on_wedged_stage():
    """A wedged mid-graph stage trips the deadline fast instead of
    hanging the driver forever, and bumps stages.drain_timeouts."""
    hang = threading.Event()
    c0 = obs.counter(names.STAGES_DRAIN_TIMEOUTS).value

    def wedge(i, v, sp):
        hang.wait(20.0)  # never set
        return v

    t0 = time.monotonic()
    with pytest.raises(DrainTimeout):
        StageGraph(
            [
                Stage("src", fn=_passthrough),
                Stage("wedge", fn=wedge, releases_window=True,
                      heartbeat_label="wedged stage"),
                Stage("sink", fn=lambda i, v, sp: None),
            ],
            window=2,
            drain_timeout_s=0.4,
        ).run(range(4))
    assert time.monotonic() - t0 < 10.0
    assert obs.counter(names.STAGES_DRAIN_TIMEOUTS).value == c0 + 1
    hang.set()


def test_run_window_acquired_at_declared_stage():
    """acquires_window on a downstream thread stage bounds items
    between THAT stage and the releaser — the source may run further
    ahead, bounded by its edge queue (the fused sweep's shape)."""
    inflight = [0]
    peak = [0]
    lock = threading.Lock()

    def dispatch(i, v, sp):
        with lock:
            inflight[0] += 1
            peak[0] = max(peak[0], inflight[0])
        return v

    def drain(i, v, sp):
        time.sleep(0.005)
        with lock:
            inflight[0] -= 1
        return v

    stats = StageGraph(
        [
            Stage("build", fn=_passthrough, out_maxsize=1),
            Stage("dispatch", fn=dispatch, acquires_window=True),
            Stage("drain", fn=drain, releases_window=True,
                  out_maxsize=2),
            Stage("sink", fn=lambda i, v, sp: None),
        ],
        window=2,
        drain_timeout_s=30.0,
    ).run(range(10))
    assert peak[0] <= 2
    assert stats["items"] == 10


def test_run_wedged_windowed_thread_stage_trips_deadline():
    """A wedged operation inside a window-acquiring THREAD stage (the
    fused sweep's dispatch shape) still trips DrainTimeout: the driver
    blocked forwarding onto the full edge polls the deadline (post-
    review fix — nothing else can observe this wedge)."""
    hang = threading.Event()

    def wedge(i, v, sp):
        hang.wait(30.0)  # never set
        return v

    t0 = time.monotonic()
    with pytest.raises(DrainTimeout):
        StageGraph(
            [
                Stage("build", fn=_passthrough, out_maxsize=1),
                Stage("dispatch", fn=wedge, acquires_window=True,
                      heartbeat_label="wedged dispatch"),
                Stage("drain", fn=_passthrough, releases_window=True,
                      out_maxsize=2),
                Stage("sink", fn=lambda i, v, sp: None),
            ],
            window=2,
            drain_timeout_s=0.4,
        ).run(range(8))
    assert time.monotonic() - t0 < 10.0
    hang.set()


def test_iterate_source_fault_site_and_span_attrs_honored():
    """Generator mode applies a SOURCE stage's declared fault_site and
    span_attrs (post-review fix): a chaos schedule against the declared
    site fires, and computed attrs land on the stage span."""
    obs.reset_all()
    with inject.armed("cw_stream_stage:raise@chunk=1"):
        got = []
        with pytest.raises(inject.InjectedFault):
            for v in StageGraph(
                [Stage("src", fn=_passthrough,
                       span=names.SPAN_CW_STREAM_STAGE,
                       fault_site=inject.SITE_PREFETCH_STAGE,
                       span_attrs=lambda i, raw: {"nbytes": raw * 10})],
                window=2,
            ).iterate(iter(range(4))):
                got.append(v)
        assert [r["site"] for r in inject.fired()] == ["cw_stream_stage"]
    assert got == [0]
    spans = [e for e in TRACER.events() if e.get("type") == "span"
             and e["name"] == "cw_stream_stage"]
    assert spans[0]["attrs"]["nbytes"] == 0
    assert spans[0]["attrs"]["chunk"] == 0


def test_run_fault_site_fires_with_index():
    """A stage's declared fault site fires inside its span with the
    item index in the schedule's trigger ctx."""
    written = []
    with inject.armed("io_write:raise@chunk=1"):
        with pytest.raises(inject.InjectedFault):
            StageGraph(
                [
                    Stage("src", fn=_passthrough),
                    Stage("w", span=names.SPAN_IO_WRITE,
                          fault_site=inject.SITE_IO_WRITE,
                          fn=lambda i, v, sp: written.append(i),
                          releases_window=True),
                ],
                window=2,
            ).run(range(4))
        rec = inject.fired()
        assert len(rec) == 1
        assert rec[0]["site"] == "io_write"
        assert rec[0]["chunk"] == 1
    assert 0 in written and 1 not in written


def test_run_per_item_trace_adopted_across_threads():
    """trace_scope mode: every stage span of item i — across every
    thread hop — carries the SAME deterministic chunk trace id."""
    obs.reset_all()
    StageGraph(
        [
            Stage("src", fn=_passthrough, span=names.SPAN_DISPATCH),
            Stage("mid", fn=_passthrough, span=names.SPAN_DRAIN,
                  releases_window=True),
            Stage("sink", fn=lambda i, v, sp: None,
                  span=names.SPAN_IO_WRITE),
        ],
        window=2,
        trace_scope="scope-x",
    ).run(range(3))
    spans = [e for e in TRACER.events() if e.get("type") == "span"
             and e["name"] in ("dispatch", "drain", "io_write")]
    assert len(spans) == 9
    for e in spans:
        i = e["attrs"]["chunk"]
        assert e["trace_id"] == chunk_trace_context("scope-x", i).trace_id


# ------------------------------------------------------- generator mode

def test_iterate_orders_and_window():
    built = [0]
    peak = [0]
    lock = threading.Lock()

    def items():
        for i in range(10):
            with lock:
                built[0] += 1
                peak[0] = max(peak[0], built[0])
            yield i

    got = []
    g = StageGraph(
        [Stage("stagex", fn=lambda i, v, sp: v * 2, index_attr="tile")],
        window=2,
    )
    for v in g.iterate(items()):
        time.sleep(0.004)
        got.append(v)
        with lock:
            built[0] -= 1
    assert got == [2 * i for i in range(10)]
    assert peak[0] <= 3  # window + the one being consumed
    assert g.stats["items"] == 10


def test_iterate_error_after_in_order_prefix():
    class Boom(Exception):
        pass

    def items():
        yield 0
        yield 1
        raise Boom("build failed")

    got = []
    with pytest.raises(Boom, match="build failed"):
        for v in StageGraph(
            [Stage("s", fn=_passthrough, index_attr="tile")],
            window=2,
        ).iterate(items()):
            got.append(v)
    assert got == [0, 1]


def test_iterate_drain_timeout_and_abandon():
    hang = threading.Event()

    def wedge(i, v, sp):
        hang.wait(20.0)
        return v

    t0 = time.monotonic()
    with pytest.raises(DrainTimeout):
        for _ in StageGraph(
            [Stage("s", fn=wedge, index_attr="tile")],
            window=2, drain_timeout_s=0.4, stall_what="test staging",
        ).iterate(iter(range(3))):
            pass
    assert time.monotonic() - t0 < 10.0
    hang.set()

    # abandon: breaking out must stop + join the worker promptly
    built = [0]

    def items():
        for i in range(100):
            built[0] += 1
            yield i

    gen = StageGraph(
        [Stage("s", fn=_passthrough, index_attr="tile")], window=2,
    ).iterate(items())
    next(gen)
    gen.close()
    time.sleep(0.3)
    assert built[0] <= 5


def test_iterate_carries_consumer_trace():
    """Worker stage spans stitch onto the trace live on the CONSUMER
    thread when the generator starts (carry()/adopt())."""
    from pta_replicator_tpu.obs.trace import adopt

    obs.reset_all()
    ctx = chunk_trace_context("consumer-scope", 7)
    with adopt(ctx):
        got = list(StageGraph(
            [Stage("s", fn=_passthrough, span=names.SPAN_CW_STREAM_STAGE,
                   index_attr="tile")],
            window=2,
        ).iterate(iter(range(3))))
    assert got == [0, 1, 2]
    spans = [e for e in TRACER.events() if e.get("type") == "span"
             and e["name"] == "cw_stream_stage"]
    # 3 staged tiles + the end-of-stream probe span (eos=True)
    assert len(spans) == 4
    assert spans[-1]["attrs"].get("eos") is True
    assert all(e["trace_id"] == ctx.trace_id for e in spans)


def test_iterate_fanout_broadcast_and_gather():
    """Replica fan-out: every input reaches every replica, outputs
    gather per item in replica order; a replica error re-raises after
    the in-order prefix and all workers join."""

    def stage(r, i, v, sp):
        return (r, v)

    g = StageGraph(
        [
            Stage("build", fn=_passthrough, index_attr="tile"),
            Stage("rep", fn=stage, index_attr="tile",
                  replicas=[("A", "a"), ("B", "b")]),
        ],
        window=2,
    )
    got = list(g.iterate(iter(range(5))))
    assert got == [[("A", i), ("B", i)] for i in range(5)]

    class Boom(Exception):
        pass

    def flaky(r, i, v, sp):
        if r == "B" and i == 2:
            raise Boom("replica failed")
        return (r, v)

    got = []
    with pytest.raises(Boom):
        for item in StageGraph(
            [
                Stage("build", fn=_passthrough, index_attr="tile"),
                Stage("rep", fn=flaky, index_attr="tile",
                      replicas=[("A", "a"), ("B", "b")]),
            ],
            window=2,
        ).iterate(iter(range(6))):
            got.append(item)
    assert got == [[("A", i), ("B", i)] for i in range(len(got))]
    assert len(got) < 6


# -------------------------------------------------- telemetry + config

def test_stages_gauges_updated():
    obs.reset_all()
    StageGraph(
        [
            Stage("srcstage", fn=_passthrough),
            Stage("sinkstage", fn=lambda i, v, sp: None,
                  releases_window=True),
        ],
        window=2,
    ).run(range(4))
    busy = obs.gauge(names.STAGES_BUSY_S, stage="sinkstage").value
    assert busy >= 0.0
    edge = obs.gauge(names.STAGES_EDGE_INFLIGHT,
                     edge="srcstage->sinkstage").value
    assert edge >= 0


def test_graph_validation_errors():
    ok = Stage("s", fn=_passthrough)
    with pytest.raises(ValueError, match="at least one stage"):
        StageGraph([])
    with pytest.raises(ValueError, match="window"):
        StageGraph([ok], window=0)
    with pytest.raises(ValueError, match="final stage"):
        StageGraph([
            Stage("r", fn=_passthrough, replicas=[("A", "a")]),
            Stage("t", fn=_passthrough),
        ])
    with pytest.raises(ValueError, match="acquire"):
        StageGraph([
            Stage("a", fn=_passthrough, acquires_window=True),
            Stage("b", fn=_passthrough, acquires_window=True),
        ])
    with pytest.raises(ValueError, match="generator-mode"):
        StageGraph([
            Stage("src", fn=_passthrough),
            Stage("r", fn=_passthrough, replicas=[("A", "a")]),
        ]).run(range(2))


def test_regress_directions_for_stages_series():
    from pta_replicator_tpu.obs.regress import metric_direction

    assert metric_direction("fused.overlap_efficiency_e2e") is True
    assert metric_direction("stacked.overlap_efficiency_e2e") is True
    assert metric_direction("fused.stall_s") is False
    assert metric_direction("fused.window_wait_s") is False


# ------------------------------------------- fused sweep identity ladder

@pytest.fixture()
def streamed_cw_sweep():
    """A small streamed-CW recipe: the shape whose per-chunk static
    build the fused graph overlaps with compute/readback/write."""
    b = synthetic_batch(npsr=4, ntoa=64, seed=2)
    rng = np.random.default_rng(1)
    ncw = 32
    params = np.stack([
        np.arccos(rng.uniform(-1, 1, ncw)),
        rng.uniform(0, 2 * np.pi, ncw),
        10 ** rng.uniform(8, 9.5, ncw),
        rng.uniform(50, 1000, ncw),
        10 ** rng.uniform(-8.8, -7.6, ncw),
        rng.uniform(0, 2 * np.pi, ncw),
        rng.uniform(0, np.pi, ncw),
        np.arccos(rng.uniform(-1, 1, ncw)),
    ])
    recipe = Recipe(
        efac=jnp.ones(4),
        rn_log10_amplitude=jnp.full(4, -14.0),
        rn_gamma=jnp.full(4, 4.0),
        cgw_params=jnp.asarray(params),
        cgw_stream_chunk=8,
    )
    return b, recipe, jax.random.PRNGKey(5)


def test_fused_sweep_byte_identical_across_depths(
    tmp_path, streamed_cw_sweep
):
    """The fused graph's checkpoints, sidecars, and returned array are
    byte-for-byte the stacked path's, at depths 1/2/4 — the per-chunk
    static rebuild is bitwise the one-time precompute."""
    b, recipe, key = streamed_cw_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ref_ck,
                pipeline_depth=1)
    ref_npz = open(ref_ck, "rb").read()
    ref_meta = open(ref_ck + ".meta.json", "rb").read()
    for depth in (2, 4):
        ck = str(tmp_path / f"fused{depth}.npz")
        out = sweep(key, b, recipe, nreal=16, chunk=4,
                    checkpoint_path=ck, pipeline_depth=depth,
                    fused_stream=True)
        assert open(ck, "rb").read() == ref_npz
        assert open(ck + ".meta.json", "rb").read() == ref_meta
        np.testing.assert_array_equal(out, ref)
        assert glob.glob(ck + ".chunk*") == []


def test_fused_sweep_emits_static_build_spans(tmp_path, streamed_cw_sweep):
    """One static_build span per chunk, on the fused path only, and the
    sweep_pipeline span carries the fused stats (static_build in
    stage_busy_s)."""
    b, recipe, key = streamed_cw_sweep
    obs.reset_all()
    sweep(key, b, recipe, nreal=8, chunk=4,
          checkpoint_path=str(tmp_path / "f.npz"),
          pipeline_depth=2, fused_stream=True)
    spans = [e for e in TRACER.events() if e.get("type") == "span"]
    builds = [e for e in spans if e["name"] == names.SPAN_STATIC_BUILD]
    assert [e["attrs"]["chunk"] for e in builds] == [0, 1]
    pipeline = [e for e in spans if e["name"] == "sweep_pipeline"]
    assert len(pipeline) == 1
    assert pipeline[0]["attrs"]["fused"] is True
    assert "static_build" in pipeline[0]["attrs"]["stage_busy_s"]
    # chunk traces mean the same thing fused or not: the dispatch span
    # of chunk i carries the deterministic (checkpoint_path, i) trace
    disp = [e for e in spans if e["name"] == "dispatch"]
    ck = str(tmp_path / "f.npz")
    for e in disp:
        assert e["trace_id"] == chunk_trace_context(
            ck, e["attrs"]["chunk"]
        ).trace_id


def test_fused_sweep_crash_resume_byte_identical(
    tmp_path, streamed_cw_sweep, monkeypatch
):
    """Kill a fused sweep between chunk file and sidecar; a fused
    resume recomputes only the unrecorded chunks and matches the
    uninterrupted run bitwise (the crash-resume contract holds through
    the fused graph)."""
    import importlib

    sweep_mod = importlib.import_module("pta_replicator_tpu.utils.sweep")
    b, recipe, key = streamed_cw_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ref_ck)

    class _KillSim(BaseException):
        pass

    orig = sweep_mod._atomic_write
    seen = {"json": 0}

    def bombed(write_fn, final_path, suffix, durable=False):
        if suffix == ".json":
            seen["json"] += 1
            if seen["json"] == 2:
                raise _KillSim()
        return orig(write_fn, final_path, suffix, durable=durable)

    monkeypatch.setattr(sweep_mod, "_atomic_write", bombed)
    ck = str(tmp_path / "crash.npz")
    with pytest.raises(_KillSim):
        sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck,
              pipeline_depth=2, fused_stream=True, chunk_retries=0)
    monkeypatch.undo()

    calls = []
    out = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck,
                pipeline_depth=2, fused_stream=True,
                progress=lambda d, t: calls.append(d))
    assert calls == [2, 3, 4]  # chunk 0 survived; 1..3 recomputed
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_fused_sweep_absorbs_transient_fault_byte_identical(
    tmp_path, streamed_cw_sweep
):
    """A transient injected chunk failure on the fused path is absorbed
    by the supervised-recovery loop (same sites, same schedule meaning)
    and the recovered checkpoint stays byte-identical."""
    b, recipe, key = streamed_cw_sweep
    ref_ck = str(tmp_path / "ref.npz")
    ref = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ref_ck)
    ck = str(tmp_path / "chaos.npz")
    from pta_replicator_tpu.faults.retry import RetryPolicy

    with inject.armed("dispatch:raise@chunk=1"):
        out = sweep(key, b, recipe, nreal=16, chunk=4,
                    checkpoint_path=ck, pipeline_depth=2,
                    fused_stream=True, chunk_retries=2,
                    retry_policy=RetryPolicy(base_delay_s=0.01,
                                             max_delay_s=0.05))
        assert [r["site"] for r in inject.fired()] == ["dispatch"]
    np.testing.assert_array_equal(out, ref)
    assert open(ck, "rb").read() == open(ref_ck, "rb").read()


def test_fused_sweep_rejects_depth1(tmp_path, streamed_cw_sweep):
    """Depth 1 has no concurrency for the static build to overlap with;
    the mesh refusal is GONE (r17: fused streaming composes with a
    mesh — see tests/test_multichip.py for the fused-mesh identity)."""
    b, recipe, key = streamed_cw_sweep
    with pytest.raises(ValueError, match="pipeline_depth"):
        sweep(key, b, recipe, nreal=8, chunk=4,
              checkpoint_path=str(tmp_path / "x.npz"),
              pipeline_depth=1, fused_stream=True)


# ----------------------------------------------- fan_out (r17 writers)

def test_fan_out_preserves_task_order():
    """Results land at their task's index regardless of which worker
    ran it or in what order workers finished."""
    import random

    def task(k):
        def run():
            time.sleep(random.uniform(0, 0.01))
            return k * k
        return run

    assert fan_out([task(k) for k in range(20)], workers=4) == \
        [k * k for k in range(20)]
    assert fan_out([]) == []
    assert fan_out([task(3)], workers=8) == [9]  # workers clamp to tasks


def test_fan_out_serial_path_matches_parallel():
    assert fan_out([lambda k=k: k + 1 for k in range(5)], workers=1) == \
        fan_out([lambda k=k: k + 1 for k in range(5)], workers=5)


def test_fan_out_first_error_wins_and_stops_dispatch():
    """A failing task re-raises on the caller; tasks not yet started
    are abandoned (no half-pool wedge), started peers run to term."""
    ran = []

    def ok(k):
        def run():
            ran.append(k)
            return k
        return run

    def boom():
        raise RuntimeError("writer died")

    tasks = [ok(0), boom] + [ok(k) for k in range(2, 40)]
    with pytest.raises(RuntimeError, match="writer died"):
        fan_out(tasks, workers=2)
    assert 0 in ran and len(ran) < 39  # tail abandoned after the error


def test_fan_out_busy_gauge_returns_to_zero():
    from pta_replicator_tpu.obs import gauge

    obs.reset_all()
    fan_out([lambda: time.sleep(0.01) for _ in range(6)], workers=3,
            busy_gauge=names.SWEEP_SHARD_WRITERS_BUSY)
    assert gauge(names.SWEEP_SHARD_WRITERS_BUSY).value == 0


def test_fan_out_inherits_trace_context():
    """Spans emitted inside fan_out workers inherit the caller's trace
    identity across the thread hop — the shard_write spans of chunk i
    must ride chunk i's trace, exactly like every other stage hop."""
    from pta_replicator_tpu.obs import span
    from pta_replicator_tpu.obs.trace import adopt

    def emit():
        with span("inner"):
            pass

    obs.reset_all()
    ctx = chunk_trace_context("/tmp/t.npz", 0)
    with adopt(ctx), span("outer"):
        fan_out([emit for _ in range(3)], workers=3)
    spans = [e for e in TRACER.events() if e.get("type") == "span"]
    inners = [e for e in spans if e["name"] == "inner"]
    assert len(inners) == 3
    assert all(e["trace_id"] == ctx.trace_id for e in inners)


def test_cli_fused_stream_requires_checkpoint():
    """--fused-stream without --checkpoint refuses (before ingest)
    instead of silently running the unfused realize path."""
    from pta_replicator_tpu.__main__ import main

    with pytest.raises(SystemExit, match="fused-stream needs"):
        main(["realize", "--pardir", "/nonexistent", "--timdir",
              "/nonexistent", "--recipe", "/nonexistent.json",
              "--nreal", "4", "--out", "/tmp/never.npz",
              "--fused-stream"])


def test_cli_fused_stream_requires_depth2():
    """--fused-stream --pipeline-depth 1 refuses before ingest — the
    sweep would refuse anyway, but only after loading datasets."""
    from pta_replicator_tpu.__main__ import main

    with pytest.raises(SystemExit, match="pipeline-depth"):
        main(["realize", "--pardir", "/nonexistent", "--timdir",
              "/nonexistent", "--recipe", "/nonexistent.json",
              "--nreal", "4", "--out", "/tmp/never.npz",
              "--checkpoint", "/tmp/never_ck.npz",
              "--fused-stream", "--pipeline-depth", "1"])


def test_cli_fused_stream_accepts_mesh_shape():
    """--fused-stream --mesh-shape parses and reaches ingest (r17 lifts
    the mesh refusal): the pre-ingest gates pass and the next failure
    is the nonexistent pardir, not a fused/mesh refusal."""
    from pta_replicator_tpu.__main__ import main

    with pytest.raises((SystemExit, OSError, ValueError)) as exc_info:
        main(["realize", "--pardir", "/nonexistent", "--timdir",
              "/nonexistent", "--recipe", "/nonexistent.json",
              "--nreal", "4", "--out", "/tmp/never.npz",
              "--checkpoint", "/tmp/never_ck.npz",
              "--fused-stream", "--mesh-shape", "2x2"])
    assert "fused" not in str(exc_info.value)
