"""One-timeline merger (obs/timeline.py) and the live scrape endpoint
(obs/serve.py): the acceptance path is a real pipelined mesh sweep
(depth 2, the conftest-forced 8-virtual-device CPU mesh) captured and
merged into a single valid chrome trace with per-device stage tracks
in sort order and chunk flow links; the endpoint survives a torn-read
hammer while serving parseable Prometheus text."""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pta_replicator_tpu import obs
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.obs import names, occupancy
from pta_replicator_tpu.obs.serve import ROUTES, serve_directory, serve_url
from pta_replicator_tpu.obs.timeline import build_timeline, write_timeline


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


def _mesh_sweep_capture(tmp_path) -> str:
    """A small but REAL pipelined mesh sweep (depth 2, 4x2 mesh over
    the 8 virtual CPU devices) plus a mesh CW stream (per-device
    staging spans), captured into a telemetry dir."""
    from pta_replicator_tpu.models.batched import (
        cw_catalog_plane_tiles_for,
        cw_stream_response,
    )
    from pta_replicator_tpu.parallel import make_mesh
    from pta_replicator_tpu.utils.sweep import sweep

    assert jax.device_count() >= 8, "conftest must force 8 host devices"
    d = str(tmp_path / "cap")
    b = synthetic_batch(npsr=4, ntoa=64, nbackend=2, seed=2)
    recipe = Recipe(efac=jnp.full((4, 2), 1.1))
    obs.start_capture(d, heartbeat_interval_s=0.1, stall_timeout_s=None)
    try:
        mesh = make_mesh(4, 2)
        sweep(jax.random.PRNGKey(5), b, recipe, nreal=16, chunk=8,
              checkpoint_path=str(tmp_path / "ck.npz"), mesh=mesh,
              pipeline_depth=2)
        # per-device stage spans (cw_stream_stage{device=}) via the
        # mesh prefetch stream
        rng = np.random.default_rng(1)
        ncw = 8
        params = [
            np.arccos(rng.uniform(-1, 1, ncw)),
            rng.uniform(0, 2 * np.pi, ncw),
            10 ** rng.uniform(8, 9.5, ncw),
            rng.uniform(50, 1000, ncw),
            10 ** rng.uniform(-8.8, -7.6, ncw),
            rng.uniform(0, 2 * np.pi, ncw),
            rng.uniform(0, np.pi, ncw),
            np.arccos(rng.uniform(-1, 1, ncw)),
        ]
        cw_stream_response(
            b, cw_catalog_plane_tiles_for(b, *params, chunk=4),
            evolve=True, mesh=make_mesh(2, 2),
        )
        time.sleep(0.15)  # at least one sampler tick lands
    finally:
        obs.finish_capture()
    return d


def test_timeline_acceptance_pipelined_mesh_sweep(tmp_path):
    """ISSUE 8 acceptance: `timeline DIR` on a capture from a pipelined
    sweep (depth 2, 8-virtual-device CPU mesh) emits ONE valid chrome
    trace containing host spans, per-device stage tracks in sort
    order, and chunk flow links."""
    d = _mesh_sweep_capture(tmp_path)
    path = write_timeline(d)
    assert path == os.path.join(d, "timeline.json")
    doc = json.loads(open(path).read())  # single valid JSON document

    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    # host spans present, incl. the multichip phase span
    xs = [e for e in events if e.get("ph") == "X"]
    span_names = {e["name"] for e in xs}
    assert {"multichip_sweep", "dispatch", "drain", "io_write",
            "cw_stream_stage"} <= span_names

    # stage tracks: named + sort-indexed in dataflow order
    thread_names = {}
    sort_index = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e["name"] == "thread_name":
            thread_names[e["tid"]] = e["args"]["name"]
        elif e["name"] == "thread_sort_index":
            sort_index[e["tid"]] = e["args"]["sort_index"]
    stage_tracks = {v: k for k, v in thread_names.items()
                    if v.startswith("stage:")}
    for stage in ("stage:dispatch", "stage:drain", "stage:io_write"):
        assert stage in stage_tracks, sorted(stage_tracks)
    # per-device staging tracks (one per mesh device used)
    dev_tracks = [v for v in stage_tracks
                  if v.startswith("stage:cw_stream_stage:dev")]
    assert len(dev_tracks) >= 2
    # dataflow order: dispatch < drain < io_write < every staging track
    rank = {v: sort_index[stage_tracks[v]] for v in stage_tracks}
    assert rank["stage:dispatch"] < rank["stage:drain"] \
        < rank["stage:io_write"]
    assert all(rank["stage:io_write"] < rank[v] for v in dev_tracks)
    # stage spans actually ride their tracks
    drain_tids = {e["tid"] for e in xs if e["name"] == "drain"}
    assert drain_tids == {stage_tracks["stage:drain"]}
    dev_span_tids = {e["tid"] for e in xs if e["name"] == "cw_stream_stage"}
    assert dev_span_tids == {stage_tracks[v] for v in dev_tracks}

    # chunk flow links: one s ... f chain per chunk, binding enclosing
    # slices on the stage tracks
    flows = [e for e in events if e.get("cat") == "chunk"]
    by_id = {}
    for f in flows:
        by_id.setdefault(f["id"], []).append(f)
    assert len(by_id) == 2  # nreal=16 / chunk=8
    for chain in by_id.values():
        phs = [f["ph"] for f in sorted(chain, key=lambda f: f["ts"])]
        assert phs[0] == "s" and phs[-1] == "f"
        assert len(phs) == 3  # dispatch -> drain -> io_write
    assert doc["otherData"]["flow_events"] == len(flows)

    # heartbeat v3 progress.json validates (acceptance), and the run's
    # series artifact is schema-clean too
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    assert checker.validate_flightrec_file(
        os.path.join(d, "progress.json"), "progress") == []
    hb = json.loads(open(os.path.join(d, "progress.json")).read())
    assert hb["schema"] >= 3 and "trends" in hb


def test_timeline_merges_device_trace_with_markers(tmp_path):
    """A capture with a managed jax.profiler trace merges its device
    events onto the wall clock via the correlation markers: every
    shifted timestamp lands inside (a neighborhood of) the capture's
    wall window, and the trace's processes are kept distinct from the
    host pid."""
    d = str(tmp_path / "cap")
    obs.start_capture(d, flight_recorder=False)
    t_before = time.time()
    try:
        with obs.devprof.device_trace():
            jnp.ones((64, 64)).sum().block_until_ready()
        with obs.span(names.SPAN_COMPUTE):
            pass
    finally:
        obs.finish_capture()
    t_after = time.time()

    meta = json.loads(open(os.path.join(d, "meta.json")).read())
    if not meta.get("device_traces"):
        pytest.skip("jax.profiler wrote no trace on this backend")
    doc = build_timeline(d)
    dev = [e for e in doc["traceEvents"]
           if e.get("pid", 0) >= (1 << 21)
           and isinstance(e.get("ts"), (int, float))]
    if not dev:
        # profiler produced a dir but no trace.json on this build —
        # the merger must have said so rather than failing silently
        assert doc["otherData"]["problems"]
        pytest.skip("no trace.json events in this jax build's output")
    lo = min(e["ts"] for e in dev) / 1e6
    hi = max(e["ts"] for e in dev) / 1e6
    # anchored at the open marker -> inside the run's wall window
    # (generous slack: profiler sessions can trail past stop_trace)
    assert t_before - 5 <= lo <= t_after + 5
    assert hi - lo < 300
    host_pids = {e.get("pid") for e in doc["traceEvents"]
                 if e.get("cat") == "host"}
    assert host_pids.isdisjoint({e["pid"] for e in dev})


def test_timeline_tolerates_empty_and_missing(tmp_path):
    doc = build_timeline(str(tmp_path / "nope"))
    assert doc["traceEvents"] == [] or isinstance(doc["traceEvents"], list)
    assert doc["otherData"]["problems"]


def test_timeline_cli_subcommand(tmp_path, capsys):
    d = str(tmp_path / "cap")
    obs.start_capture(d, flight_recorder=False)
    with obs.span(names.SPAN_COMPUTE):
        pass
    obs.finish_capture()
    from pta_replicator_tpu.__main__ import main

    main(["timeline", d])
    out = json.loads(capsys.readouterr().out)
    assert out["out"] == os.path.join(d, "timeline.json")
    assert os.path.exists(out["out"])
    assert out["host_spans"] >= 1


# --------------------------------------------------------------- serve

def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def test_serve_routes_and_read_only(tmp_path):
    d = str(tmp_path / "cap")
    os.makedirs(d)
    with open(os.path.join(d, "progress.json"), "w") as fh:
        json.dump({"schema": 3, "finished": False}, fh)
    with open(os.path.join(d, "metrics.prom"), "w") as fh:
        fh.write("# TYPE x counter\nx 1.0\n")
    srv = serve_directory(d, 0, background=True)
    try:
        base = serve_url(srv, "")
        status, body = _get(base + "/")
        assert status == 200
        assert set(json.loads(body)["endpoints"]) == (
            set(ROUTES) | {"/healthz", "/readyz"}
        )
        status, body = _get(base + "/progress")
        assert status == 200 and json.loads(body)["schema"] == 3
        status, body = _get(base + "/metrics")
        assert status == 200 and b"# TYPE x counter" in body
        for bad in ("/series", "/postmortem"):  # not written yet -> 404
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(base + bad)
            assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/../etc/passwd")
        assert exc.value.code == 404
        # write-ish methods are refused (read-only endpoint)
        req = urllib.request.Request(base + "/progress", data=b"x",
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5.0)
        assert exc.value.code == 501
    finally:
        srv.shutdown()
        srv.server_close()


def test_serve_survives_torn_read_hammer(tmp_path):
    """ISSUE 8 acceptance: hammer the endpoint while a writer thread
    atomically replaces progress.json/series.json/metrics.prom as fast
    as it can — every response must parse (JSON, and Prometheus text
    exposition for /metrics). A single torn read fails the test."""
    d = str(tmp_path / "cap")
    obs.start_capture(d, heartbeat_interval_s=0.02, stall_timeout_s=None)
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            with obs.span(names.SPAN_DISPATCH, chunk=i):
                obs.gauge(names.SWEEP_CHUNKS_DONE).set(i)
            i += 1
            time.sleep(0.001)

    w = threading.Thread(target=churn, daemon=True)
    w.start()
    srv = serve_directory(d, 0, background=True)
    try:
        base = serve_url(srv, "")
        deadline = time.monotonic() + 2.0
        reads = {"/progress": 0, "/series": 0, "/metrics": 0}
        while time.monotonic() < deadline:
            for route in list(reads):
                try:
                    status, body = _get(base + route)
                except urllib.error.HTTPError as exc:
                    assert exc.code == 404  # not written yet, never torn
                    continue
                assert status == 200
                if route == "/metrics":
                    _assert_prometheus_parses(body.decode())
                else:
                    json.loads(body)  # raises on a torn document
                reads[route] += 1
        assert all(n > 10 for n in reads.values()), reads
    finally:
        stop.set()
        srv.shutdown()
        srv.server_close()
        obs.finish_capture()
        w.join(timeout=5)


def _assert_prometheus_parses(text: str) -> dict:
    """Minimal text-exposition parser: every non-comment line must be
    `name{labels} value`; returns {name: value} (the snapshot-diff
    surface)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            if line.startswith("# TYPE"):
                assert len(line.split()) == 4, line
            continue
        name_part, _, value = line.rpartition(" ")
        assert name_part, line
        float(value)  # must be numeric
        out[name_part] = float(value)
    return out


def test_serve_prometheus_snapshot_diff(tmp_path):
    """The served exposition parses into the same name->value snapshot
    the registry reports: scrape twice around a counter bump and the
    diff is exactly that bump."""
    d = str(tmp_path / "cap")
    obs.start_capture(d, heartbeat_interval_s=0.02, stall_timeout_s=None)
    srv = serve_directory(d, 0, background=True)
    try:
        base = serve_url(srv, "")
        obs.gauge(names.SWEEP_CHUNKS_DONE).set(1)
        time.sleep(0.3)
        snap1 = _assert_prometheus_parses(_get(base + "/metrics")[1].decode())
        obs.gauge(names.SWEEP_CHUNKS_DONE).set(4)
        time.sleep(0.3)
        snap2 = _assert_prometheus_parses(_get(base + "/metrics")[1].decode())
        key = "sweep_chunks_done"
        assert snap2[key] - snap1[key] == pytest.approx(3.0)
        unchanged = set(snap1) & set(snap2) - {key, "obs_overhead_s",
                                               "proc_rss_bytes"}
        for k in unchanged:
            if k.startswith(("flightrec", "sweep")):
                assert snap1[k] == snap2[k]
    finally:
        srv.shutdown()
        srv.server_close()
        obs.finish_capture()


def test_watch_serve_cli_flag(tmp_path):
    """`watch DIR --once --serve 0` starts the endpoint for the watch's
    lifetime and still returns watch's own exit semantics."""
    d = str(tmp_path / "cap")
    os.makedirs(d)
    from pta_replicator_tpu.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["watch", d, "--once", "--serve", "0"])
    assert exc.value.code == 3  # no heartbeat yet — watch contract


def test_serve_healthz_readiness_ladder(tmp_path):
    """/healthz walks the readiness ladder truthfully: 503 no-heartbeat
    before a flight recorder writes, 200 live while the heartbeat is
    fresh, 503 stale once it ages past the bound, 503 postmortem once
    the run died (PR 11: what a load balancer or chaos harness polls)."""
    d = str(tmp_path / "cap")
    os.makedirs(d)
    srv = serve_directory(d, 0, background=True)
    srv.stale_after_s = 1.0
    try:
        url = serve_url(srv, "/healthz")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url, timeout=5.0)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["state"] == "no-heartbeat"

        with open(os.path.join(d, "progress.json"), "w") as fh:
            json.dump({"schema": 3}, fh)
        status, body = _get(url)
        doc = json.loads(body)
        assert status == 200 and doc["ok"] and doc["state"] == "live"
        assert doc["heartbeat_age_s"] >= 0

        # the SLO rung (PR 14): a fast-burn breach turns /readyz 503
        # ("slo-breach") while /healthz — pure liveness — stays 200
        with open(os.path.join(d, "slo.json"), "w") as fh:
            json.dump({"objectives": {"x": {"breach": True}},
                       "breached": ["x"]}, fh)
        status, _body = _get(url)
        assert status == 200  # healthz unchanged
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(serve_url(srv, "/readyz"),
                                   timeout=5.0)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["state"] == "slo-breach"
        os.remove(os.path.join(d, "slo.json"))
        status, _body = _get(serve_url(srv, "/readyz"))
        assert status == 200  # breach cleared: ready again

        old = time.time() - 30.0
        os.utime(os.path.join(d, "progress.json"), (old, old))
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url, timeout=5.0)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["state"] == "stale"

        with open(os.path.join(d, "postmortem.json"), "w") as fh:
            json.dump({"reason": "SIGTERM"}, fh)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url, timeout=5.0)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["state"] == "postmortem"

        # /readyz is an alias, and the index advertises the route
        _status, body = _get(serve_url(srv, "/"))
        assert "/healthz" in json.loads(body)["endpoints"]
    finally:
        srv.shutdown()
        srv.server_close()


def test_serve_readyz_folds_live_numerics_episode(tmp_path):
    """The numerics rung (PR 18): a LIVE non-finite episode — a real
    armed probe seeing NaNs, persisted by numerics.write — turns
    /readyz 503 with state "numerics" naming the site, while /healthz
    (pure liveness) stays 200; EPISODE_CLEAR_AFTER clean probe calls
    close the episode and /readyz recovers to 200."""
    from pta_replicator_tpu.obs import numerics

    d = str(tmp_path / "cap")
    os.makedirs(d)
    with open(os.path.join(d, "progress.json"), "w") as fh:
        json.dump({"schema": 3}, fh)
    srv = serve_directory(d, 0, background=True)
    try:
        numerics.reset()
        numerics.arm(clear_caches=False)
        bad = jnp.array([1.0, jnp.nan, 2.0], jnp.float32)
        numerics.probe("realization.white", bad)
        numerics.flush()
        numerics.write(d)

        status, _ = _get(serve_url(srv, "/healthz"))
        assert status == 200  # liveness unchanged by corrupt tensors
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(serve_url(srv, "/readyz"),
                                   timeout=5.0)
        assert exc.value.code == 503
        doc = json.loads(exc.value.read())
        assert doc["state"] == "numerics"
        assert doc["nonfinite_sites"] == ["realization.white"]

        # the ledger itself is scrapeable while the episode is open
        _status, body = _get(serve_url(srv, "/numerics"))
        assert json.loads(body)["episodes_active"] == ["realization.white"]

        clean = jnp.ones(3, jnp.float32)
        for _ in range(numerics.EPISODE_CLEAR_AFTER):
            numerics.probe("realization.white", clean)
        numerics.flush()
        numerics.write(d)
        status, _ = _get(serve_url(srv, "/readyz"))
        assert status == 200  # episode closed: ready again
    finally:
        numerics.reset()
        srv.shutdown()
        srv.server_close()
