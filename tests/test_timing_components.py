"""Full-timing-model refit: binary/dispersion/astrometry design columns.

VERDICT.md round-2 criterion: a post-injection fit on real B1855+09 (ELL1
binary) must absorb binary-shaped signal power the way the reference's
full-model PINT refit does (/root/reference/pta_replicator/simulate.py:
44-69), which a spin-only quadratic fit cannot.
"""
import numpy as np
import pytest

from pta_replicator_tpu import load_pulsar, make_ideal
from pta_replicator_tpu.io.par import read_par
from pta_replicator_tpu.timing.components import (
    BinaryModel,
    dispersion_delay,
    earth_position_au,
    full_design_matrix,
)
from pta_replicator_tpu.timing.fit import noise_covariance, gls_fit, wls_fit

B1855_PAR = "/root/reference/test_partim/par/B1855+09.par"
B1855_TIM = "/root/reference/test_partim/tim/B1855+09.tim"
JPSR_PAR = "/root/reference/test_partim_small/par/JPSR00.par"
JPSR_TIM = "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim"


def _rms(x):
    return float(np.sqrt(np.mean(np.asarray(x) ** 2)))


# ----------------------------------------------------------- binary physics

def test_ell1_circular_limit():
    """eps1 = eps2 = 0 reduces the ELL1 Roemer to x sin(2 pi (t-tasc)/Pb)."""
    b = BinaryModel(model="ELL1", pb_days=10.0, a1_ls=5.0, tasc_mjd=55000.0)
    t = 55000.0 + np.linspace(0, 30, 500)
    expect = 5.0 * np.sin(2 * np.pi * (t - 55000.0) / 10.0)
    np.testing.assert_allclose(b.delay_s(t), expect, atol=1e-12)


def test_ell1_matches_dd_at_low_eccentricity():
    """The ELL1 expansion agrees with the full Kepler solve to O(e^2)
    (Lange et al. 2001): eps1 = e sin(om), eps2 = e cos(om), and the DD
    epoch of periastron T0 = TASC + PB * om / (2 pi)."""
    e, om_deg, pb, x = 1e-4, 63.0, 12.3, 9.2
    om = np.deg2rad(om_deg)
    tasc = 55000.0
    ell1 = BinaryModel(
        model="ELL1", pb_days=pb, a1_ls=x, tasc_mjd=tasc,
        eps1=e * np.sin(om), eps2=e * np.cos(om),
    )
    dd = BinaryModel(
        model="DD", pb_days=pb, a1_ls=x, ecc=e, om_deg=om_deg,
        t0_mjd=tasc + pb * om / (2 * np.pi),
    )
    t = 55000.0 + np.linspace(0, 40, 800)
    d_ell1, d_dd = ell1.delay_s(t), dd.delay_s(t)
    # agreement to O(x e^2) ~ 1e-7 s, with the constant -3/2 x eta offset
    # of the expansion removed (it is absorbed by the pulse-phase offset)
    diff = (d_ell1 - d_dd) - np.mean(d_ell1 - d_dd)
    assert _rms(diff) < 5.0 * x * e**2


def test_shapiro_delay_shape():
    """Shapiro *delay* -2r ln(1 - s sin phi) peaks (most positive) at
    superior conjunction (sin phi = 1) and grows with M2."""
    kw = dict(model="ELL1", pb_days=10.0, a1_ls=5.0, tasc_mjd=55000.0,
              sini=0.999)
    t = 55000.0 + np.linspace(0, 10, 2000)
    b_light = BinaryModel(**kw, m2_msun=0.1)
    b_heavy = BinaryModel(**kw, m2_msun=0.4)
    s_light = b_light.delay_s(t) - BinaryModel(**kw).delay_s(t)
    s_heavy = b_heavy.delay_s(t) - BinaryModel(**kw).delay_s(t)
    assert abs(np.argmax(s_heavy) - np.argmax(np.sin(2 * np.pi * (t - 55000.0) / 10.0))) < 10
    ok = np.abs(s_light) > 1e-12  # skip the 0/0 zero-crossings of sin phi
    np.testing.assert_allclose(s_heavy[ok] / s_light[ok], 4.0, rtol=1e-6)


def test_dispersion_delay_scaling():
    f = np.array([400.0, 800.0, 1600.0])
    d = dispersion_delay(f, dm=10.0)
    np.testing.assert_allclose(d[0] / d[1], 4.0, rtol=1e-12)
    np.testing.assert_allclose(d[0], 10.0 / (2.41e-4 * 400.0**2), rtol=1e-12)


def test_earth_orbit_sanity():
    """|r| in [0.98, 1.02] AU, one-year periodicity, ecliptic tilt."""
    t = 51544.5 + np.linspace(0, 730, 2000)
    r = earth_position_au(t)
    d = np.linalg.norm(r, axis=-1)
    assert d.min() > 0.975 and d.max() < 1.025
    r0 = earth_position_au(np.array([51544.5, 51544.5 + 365.25]))
    assert np.linalg.norm(r0[0] - r0[1]) < 0.02
    # z-extent reflects the obliquity
    assert 0.35 < np.abs(r[:, 2]).max() < 0.45


# ------------------------------------------------------- full design matrix

def test_full_design_matrix_b1855_columns(b1855, tmp_path):
    # real TOAs/frequencies: the DMX windows are ~0.15 d wide, so only
    # real observation epochs land inside them, and the multi-band
    # frequency coverage keeps the chromatic columns non-degenerate
    par = read_par(B1855_PAR)
    t = b1855.toas.get_mjds()
    f = b1855.toas.freqs_mhz
    M, names = full_design_matrix(
        par, t, freqs_mhz=f, flags=b1855.toas.flags
    )
    for nm in ("OFFSET", "F0", "F1", "RAJ", "DECJ", "PMRA", "PMDEC", "PX",
               "FD1", "FD2", "FD3", "PB", "A1", "TASC", "EPS1", "EPS2",
               "M2", "SINI", "JUMP1"):
        assert nm in names, nm
    assert "DM" not in names  # collinear with the all-covering DMX set
    assert sum(nm.startswith("DMX_") for nm in names) > 100
    assert M.shape == (len(t), len(names))
    assert np.all(np.isfinite(M))

    # with the DMX windows stripped, the global DM column appears
    stripped = tmp_path / "nodmx.par"
    with open(B1855_PAR) as fh, open(stripped, "w") as out:
        for line in fh:
            if not line.startswith(("DMX_", "DMXR1_", "DMXR2_")):
                out.write(line)
    M, names = full_design_matrix(read_par(str(stripped)), t, freqs_mhz=f)
    assert "DM" in names


# ------------------------------------------- the B1855+09 refit criterion

@pytest.fixture(scope="module")
def b1855():
    psr = load_pulsar(B1855_PAR, B1855_TIM)
    make_ideal(psr)
    return psr


def test_b1855_loads_with_binary_model(b1855):
    m = b1855.model
    assert m.binary is not None and m.binary.model == "ELL1"
    assert m.binary.pb_days == pytest.approx(12.327171191603620594)
    assert _rms(b1855.residuals.resids_value) < 1e-9


def test_b1855_binary_refit_absorbs_orbital_signal(b1855):
    """Inject an exact A1/EPS1 perturbation signal; the full-model fit
    absorbs it (>100x rms reduction) and recovers the parameter offsets,
    while the spin-only fit cannot absorb orbital harmonics."""
    import copy

    psr = copy.deepcopy(b1855)
    t = psr.toas.get_mjds()
    b = psr.model.binary
    dA1, dEPS1 = 3e-7, 2e-8
    signal = (
        b.replace("A1", b.a1_ls + dA1).replace("EPS1", b.eps1 + dEPS1).delay_s(t)
        - b.delay_s(t)
    )
    psr.inject("orbital_error", {}, signal)
    pre = _rms(psr.residuals.resids_value)

    spin_only = copy.deepcopy(psr)
    spin_only.fit(fitter="wls", params="spin")
    post_spin = _rms(spin_only.residuals.resids_value)

    psr.fit(fitter="wls", params="full")
    post_full = _rms(psr.residuals.resids_value)

    assert post_full < pre / 100.0
    assert post_full < post_spin / 10.0  # spin fit can't absorb the orbit
    assert psr.fit_results["A1"] == pytest.approx(dA1, rel=5e-2)
    assert psr.fit_results["EPS1"] == pytest.approx(dEPS1, rel=5e-2)
    # the fitted parameters persisted to the par representation
    assert float(psr.par.params["A1"][0]) == pytest.approx(b.a1_ls + dA1, rel=1e-9)


def test_b1855_dm_refit(tmp_path):
    """On a DMX-less model the global DM column carries the 1/f^2
    signature: strip B1855's DMX windows, inject a DM offset across the
    real multi-band TOAs, and the full fit recovers it."""
    from pta_replicator_tpu import load_pulsar

    stripped = tmp_path / "b1855_nodmx.par"
    with open(B1855_PAR) as fh, open(stripped, "w") as out:
        for line in fh:
            if not line.startswith(("DMX_", "DMXR1_", "DMXR2_")):
                out.write(line)
    psr = load_pulsar(str(stripped), B1855_TIM)
    make_ideal(psr)
    assert psr.par.dmx_windows == []

    dDM = 1e-4
    psr.inject(
        "dm_error", {},
        np.asarray(dispersion_delay(psr.toas.freqs_mhz, dDM), np.float64),
    )
    assert np.std(psr.toas.freqs_mhz) > 50.0  # real multi-band data
    psr.fit(fitter="wls", params="full")
    assert psr.fit_results["DM"] == pytest.approx(dDM, rel=5e-2)
    assert _rms(psr.residuals.resids_value) < 1e-7


def test_astrometry_refit_jpsr():
    """An annual sky-position-offset signature is absorbed by the full
    fit on the small fixture pulsar."""
    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    t = psr.toas.get_mjds()
    from pta_replicator_tpu.timing.components import astrometry_columns

    cols, names = astrometry_columns(
        t, psr.model.ra_rad, psr.model.dec_rad, psr.model.pepoch_mjd
    )
    dra = 5e-9  # rad
    psr.inject("pos_error", {}, np.asarray(cols[0] * dra, np.float64))
    pre = _rms(psr.residuals.resids_value)
    psr.fit(fitter="wls", params="full")
    assert _rms(psr.residuals.resids_value) < pre / 5.0
    assert psr.fit_results["RAJ"] == pytest.approx(dra, rel=0.3)


# ------------------------------------------------------------ GLS refit

def test_gls_covariance_blocks():
    """The assembled covariance has the white diagonal, the ECORR epoch
    blocks, and the red-noise long-timescale structure."""
    n = 40
    rng = np.random.default_rng(2)
    errors = np.full(n, 1e-6)
    epoch_index = np.repeat(np.arange(10), 4)
    toas = np.sort(rng.uniform(0, 3.16e8, n))
    C = noise_covariance(
        errors, efac=1.2, equad_s=5e-7, ecorr_s=2e-6,
        epoch_index=epoch_index,
        rn_log10_amplitude=-13.0, rn_gamma=4.0, toas_s=toas, rn_nmodes=15,
    )
    assert C.shape == (n, n)
    np.testing.assert_allclose(C, C.T)
    assert np.all(np.linalg.eigvalsh(C) > 0)
    # white part on the diagonal
    white = (1.2 * 1e-6) ** 2 + (5e-7) ** 2
    assert np.all(np.diag(C) > white)
    # same-epoch pairs carry the ECORR block; different-epoch pairs don't
    C_noRN = noise_covariance(
        errors, efac=1.2, equad_s=5e-7, ecorr_s=2e-6,
        epoch_index=epoch_index,
    )
    assert C_noRN[0, 1] == pytest.approx((2e-6) ** 2)
    assert C_noRN[0, 5] == 0.0


def test_gls_vs_wls_differ_on_red_noise():
    """VERDICT criterion: with a realistic (red-noise-dominated)
    covariance, GLS and WLS produce measurably different fits."""
    from pta_replicator_tpu import add_red_noise
    from pta_replicator_tpu.timing.fit import design_matrix

    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    add_red_noise(psr, -12.8, 5.0, seed=42)
    res = psr.residuals.resids_value
    toas_s = ((psr.toas.get_mjds() - psr.model.pepoch_mjd) * 86400.0).astype(float)
    M = design_matrix(toas_s, psr.model.f0, nspin=2)
    C = noise_covariance(
        psr.toas.errors_s,
        rn_log10_amplitude=-12.8, rn_gamma=5.0,
        toas_s=psr.toas.get_mjds() * 86400.0, rn_nmodes=30,
    )
    p_wls, post_wls = wls_fit(res, psr.toas.errors_s, M)
    p_gls, post_gls = gls_fit(res, C, M)
    # the fits must disagree by far more than numerical noise: the GLS
    # weighting knows the low-frequency power is noise, not signal
    rel = np.abs(np.asarray(p_wls) - np.asarray(p_gls)) / (
        np.abs(np.asarray(p_wls)) + 1e-30
    )
    assert float(np.max(rel)) > 1e-3


def test_covariance_from_recipe_per_backend():
    """VERDICT r2 item 7: a multi-backend pulsar's GLS covariance must
    carry each TOA's own backend EFAC/EQUAD/ECORR — not the table mean."""
    from pta_replicator_tpu.batch import freeze
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.timing.fit import covariance_from_recipe

    psr = load_pulsar(B1855_PAR, B1855_TIM)
    batch = freeze([psr])
    nb = len(batch.backend_names)
    assert nb >= 2, "B1855+09 must have multiple backends"

    efac = np.linspace(0.8, 1.6, nb)
    log10_eq = np.linspace(-6.8, -6.2, nb)
    log10_ec = np.linspace(-6.9, -6.4, nb)
    recipe = Recipe(
        efac=efac[None, :],
        log10_equad=log10_eq[None, :],
        log10_ecorr=log10_ec[None, :],
    )
    C = covariance_from_recipe(
        psr, recipe, psr_index=0, backend_names=batch.backend_names
    )
    n = psr.toas.ntoas
    idx = np.asarray(batch.backend_index[0][:n])
    sigma = psr.toas.errors_s

    # epoch structure + first-TOA-of-epoch backend (the reference's
    # quantize_fast labels each epoch by its first member's flag,
    # white_noise.py:33-35; the freeze step uses the same rule)
    from pta_replicator_tpu.ops.quantize import quantize

    mjds = psr.toas.get_mjds()
    bins = quantize(mjds, dt=0.1)
    ep = bins.epoch_index
    order = np.argsort(mjds, kind="stable")
    uniq_e, first_pos = np.unique(ep[order], return_index=True)
    epoch_backend = np.zeros(bins.nepochs, dtype=np.int64)
    epoch_backend[uniq_e] = idx[order[first_pos]]

    # t2equad (Recipe default): EFAC scales EQUAD too — the same
    # variance white_noise_delays injects
    white = (efac[idx] * sigma) ** 2 + (
        efac[idx] * 10.0 ** log10_eq[idx]
    ) ** 2
    ecorr2 = (10.0 ** log10_ec[epoch_backend[ep]]) ** 2
    np.testing.assert_allclose(np.diag(C), white + ecorr2, rtol=1e-10)

    # the scalarized (mean) weighting must NOT reproduce this diagonal
    mean_white = (efac.mean() * sigma) ** 2 + (
        efac.mean() * 10.0 ** np.mean(log10_eq)
    ) ** 2 + (10.0 ** np.mean(log10_ec)) ** 2
    assert not np.allclose(np.diag(C), mean_white, rtol=1e-3, atol=0.0)

    # same-epoch cross terms carry that epoch's backend ECORR^2
    pair = None
    for e in range(bins.nepochs):
        where = np.nonzero(ep == e)[0]
        if len(where) >= 2:
            pair = (where[0], where[1])
            break
    assert pair is not None
    i, j = pair
    np.testing.assert_allclose(
        C[i, j], (10.0 ** log10_ec[epoch_backend[ep[i]]]) ** 2, rtol=1e-10
    )

    # per-pulsar arrays without context must fail loudly, not average
    with pytest.raises(ValueError, match="psr_index"):
        covariance_from_recipe(psr, recipe)
    with pytest.raises(ValueError, match="backend_names"):
        covariance_from_recipe(psr, recipe, psr_index=0)


def test_covariance_from_recipe():
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.timing.fit import covariance_from_recipe

    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    recipe = Recipe(
        efac=np.asarray(1.1), log10_equad=np.asarray(-6.5),
        log10_ecorr=np.asarray(-6.8),
        rn_log10_amplitude=np.asarray(-14.0), rn_gamma=np.asarray(4.0),
    )
    C = covariance_from_recipe(psr, recipe)
    assert C.shape == (psr.toas.ntoas,) * 2
    assert np.all(np.linalg.eigvalsh(C) > 0)
    psr.fit(fitter="gls", cov=C)  # end-to-end GLS refit runs


def test_fit_gls_builds_covariance_from_recipe():
    """fit(fitter='gls', recipe=...) assembles the exact noise covariance
    internally (same result as passing covariance_from_recipe output)."""
    import copy

    from pta_replicator_tpu import add_red_noise
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.timing.fit import covariance_from_recipe

    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    add_red_noise(psr, -13.0, 4.0, seed=7)
    recipe = Recipe(
        efac=np.asarray(1.1),
        rn_log10_amplitude=np.asarray(-13.0),
        rn_gamma=np.asarray(4.0),
    )
    a, b = copy.deepcopy(psr), copy.deepcopy(psr)
    a.fit(fitter="gls", recipe=recipe)
    b.fit(fitter="gls", cov=covariance_from_recipe(b, recipe))
    np.testing.assert_allclose(
        a.residuals.resids_value, b.residuals.resids_value, rtol=0, atol=1e-15
    )


def test_b1855_jump_refit(b1855):
    """A receiver-jump perturbation (offset on the -fe L-wide TOAs, the
    JUMP the real par declares) is absorbed by the full fit and
    recovered; the spin-only fit cannot absorb a backend step."""
    import copy

    psr = copy.deepcopy(b1855)
    assert psr.par.jumps == [("fe", "L-wide", pytest.approx(-1.717050495e-05))]
    match = np.asarray(
        [f.get("fe") == "L-wide" for f in psr.toas.flags], dtype=float
    )
    assert 0 < match.sum() < len(match)  # genuinely multi-receiver data
    dJ = 5e-7
    psr.inject("jump_error", {}, dJ * match)
    pre = _rms(psr.residuals.resids_value)

    spin_only = copy.deepcopy(psr)
    spin_only.fit(fitter="wls", params="spin")
    post_spin = _rms(spin_only.residuals.resids_value)

    psr.fit(fitter="wls", params="full")
    post_full = _rms(psr.residuals.resids_value)

    assert "JUMP1" in psr.fit_results
    assert post_full < pre / 50.0
    assert post_full < post_spin / 5.0
    assert psr.fit_results["JUMP1"] == pytest.approx(dJ, rel=5e-2)
    # the fitted jump persisted to the par line (write_partim fidelity):
    # new value = declared value + exactly the fitted update
    assert psr.par.jumps[0][2] == pytest.approx(
        -1.7170504954499434e-05 + psr.fit_results["JUMP1"], abs=1e-18
    )


def test_degenerate_jump_column_skipped():
    """A JUMP covering ALL loaded TOAs would duplicate OFFSET (rank
    deficiency -> arbitrary persisted value); the design matrix must
    skip it while keeping positional names for the remaining jumps."""
    par = read_par(B1855_PAR)
    t = np.linspace(53400, 57500, 50)
    f = np.full(50, 1400.0)
    # every TOA matches JUMP1's flag -> degenerate
    flags_all = [{"fe": "L-wide"} for _ in range(50)]
    _, names = full_design_matrix(par, t, freqs_mhz=f, flags=flags_all)
    assert "JUMP1" not in names
    # half the TOAs match -> the column exists
    flags_half = [
        {"fe": "L-wide" if i % 2 else "430"} for i in range(50)
    ]
    _, names = full_design_matrix(par, t, freqs_mhz=f, flags=flags_half)
    assert "JUMP1" in names


def test_b1855_fd_refit(b1855):
    """An FD-shaped (chromatic profile-evolution) perturbation is
    absorbed by the full fit and its coefficient recovered."""
    import copy

    from pta_replicator_tpu.timing.components import fd_column

    psr = copy.deepcopy(b1855)
    assert len(psr.par.fd_terms) == 3
    dFD1 = 2e-5
    psr.inject(
        "fd_error", {},
        np.asarray(dFD1 * fd_column(psr.toas.freqs_mhz, 1), np.float64),
    )
    psr.fit(fitter="wls", params="full")
    assert psr.fit_results["FD1"] == pytest.approx(dFD1, rel=0.1)
    assert _rms(psr.residuals.resids_value) < 1e-7
    # write-back: par FD1 = declared + fitted
    assert psr.par.fd_terms[0] == pytest.approx(
        0.00011146578515037641 + psr.fit_results["FD1"], abs=1e-18
    )


def test_b1855_dmx_refit(b1855):
    """Windowed DM offsets (the NANOGrav DMX model, 147 windows on this
    par) are fitted per-window; a global DM shift is absorbed as a
    near-uniform DMX update, and the global DM column is absent (it
    would be collinear with the all-covering windows)."""
    import copy

    psr = copy.deepcopy(b1855)
    assert len(psr.par.dmx_windows) == 147
    dDM = 1e-4
    from pta_replicator_tpu.timing.components import dispersion_delay

    psr.inject(
        "dm_error", {},
        np.asarray(dispersion_delay(psr.toas.freqs_mhz, dDM), np.float64),
    )
    psr.fit(fitter="wls", params="full")
    assert "DM" not in psr.fit_results
    fitted = [
        v for k, v in psr.fit_results.items() if k.startswith("DMX_")
    ]
    assert len(fitted) > 100  # most windows hold TOAs
    assert np.median(fitted) == pytest.approx(dDM, rel=0.05)
    assert _rms(psr.residuals.resids_value) < 1e-7


def test_covariance_from_recipe_chromatic():
    """GLS covariance includes the chromatic (DM-like) red-noise block
    for recipes that inject it: per-TOA variance of many oracle
    chromatic draws must match the chromatic covariance diagonal, and
    the block carries the (ref/f)^idx frequency scaling."""
    from pta_replicator_tpu import add_chromatic_noise
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.timing.fit import covariance_from_recipe

    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    # spread the observing frequencies so the chromatic scaling is visible
    psr.toas.freqs_mhz[:] = np.linspace(700.0, 2100.0, psr.toas.ntoas)

    amp, gam, cidx = -13.2, 3.5, 2.0
    base = Recipe()
    recipe = Recipe(
        chrom_log10_amplitude=np.asarray(amp),
        chrom_gamma=np.asarray(gam),
        chrom_index=np.asarray(cidx),
    )
    C0 = covariance_from_recipe(psr, base)
    C = covariance_from_recipe(psr, recipe)
    block = np.asarray(C - C0)
    assert np.all(np.linalg.eigvalsh(block) > -1e-20)  # PSD chromatic term

    # frequency scaling: diag ~ (1400/f)^(2*idx) times the achromatic form
    s = (1400.0 / psr.toas.freqs_mhz) ** cidx
    d = np.diag(block)
    ratio = d / s**2
    # after dividing out the scaling, the diagonal is the achromatic
    # basis quadratic form — smooth in time, not in frequency; compare
    # low-f vs high-f TOAs interleaved in time
    assert np.corrcoef(d, s**2)[0, 1] > 0.2  # scaling visible
    assert ratio.std() / ratio.mean() < 1.0

    # Monte-Carlo variance check against the oracle injection
    nmc = 400
    draws = np.empty((nmc, psr.toas.ntoas))
    for i in range(nmc):
        import copy

        p2 = load_pulsar(JPSR_PAR, JPSR_TIM)
        p2.toas.freqs_mhz[:] = psr.toas.freqs_mhz
        make_ideal(p2)
        add_chromatic_noise(p2, amp, gam, chromatic_index=cidx, seed=1000 + i)
        draws[i] = p2.added_signals_time[f"{p2.name}_chromatic_noise"]
    mc_var = draws.var(axis=0)
    # aggregate bound (per-TOA MC error at nmc=400 is ~7%)
    assert np.mean(mc_var) == pytest.approx(np.mean(d), rel=0.15)
    # and the frequency shape of the variance follows the covariance
    assert np.corrcoef(mc_var, d)[0, 1] > 0.9


def test_fit_damping_semantics():
    """max_step_halvings=0 applies the full Newton step unconditionally
    (plain iterated WLS), and fit_results always reflects the scale that
    was actually written to the par/model."""
    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    t = (psr.toas.get_mjds() - psr.model.pepoch_mjd) * 86400.0
    psr.inject("spin_err", {}, 3e-13 * t)
    f0_before = psr.model.f0
    psr.fit(fitter="wls", params="spin", max_step_halvings=0)
    # the full step was applied: model moved by exactly fit_results
    assert psr.model.f0 == f0_before - psr.fit_results["F0"]
    assert np.std(psr.residuals.resids_value) < 1e-8


def test_fit_damping_rolls_back_loc():
    """A damped (rejected-then-halved) step on an ecliptic pulsar must
    not leak the rejected step's sky position into self.loc (the
    rollback restores par, model, AND loc together)."""
    import os

    par = "/root/reference/test_partim/par/B1855+09.par"
    tim = "/root/reference/test_partim/tim/B1855+09.tim"
    if not (os.path.isfile(par) and os.path.isfile(tim)):
        import pytest as _pytest

        _pytest.skip("B1855 fixture absent")
    psr = load_pulsar(par, tim)
    # real-data fit from the raw par: steps get damped (chi2-gated)
    psr.fit(fitter="wls", niter=2)
    from pta_replicator_tpu.io.par import _parse_float

    # loc stays consistent with the par's ELONG/ELAT after the fit
    assert psr.loc["ELONG"] == _parse_float(psr.par.params["ELONG"][0])
    assert psr.loc["ELAT"] == _parse_float(psr.par.params["ELAT"][0])


def test_covariance_equad_convention_matches_injection():
    """t2equad (Recipe default) scales EQUAD by EFAC in the injected
    variance (white_noise.py:64-76); the GLS covariance must weight the
    same variance, and tnequad=True must weight the unscaled form."""
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.timing.fit import covariance_from_recipe

    psr = load_pulsar(JPSR_PAR, JPSR_TIM)
    make_ideal(psr)
    ef, lq = 2.0, -6.0
    t2 = Recipe(efac=np.asarray(ef), log10_equad=np.asarray(lq))
    tn = Recipe(efac=np.asarray(ef), log10_equad=np.asarray(lq),
                tnequad=True)
    d_t2 = np.diag(covariance_from_recipe(psr, t2))
    d_tn = np.diag(covariance_from_recipe(psr, tn))
    sig2 = psr.toas.errors_s**2
    np.testing.assert_allclose(
        d_t2, ef**2 * (sig2 + 10.0 ** (2 * lq)), rtol=1e-12)
    np.testing.assert_allclose(
        d_tn, ef**2 * sig2 + 10.0 ** (2 * lq), rtol=1e-12)
