"""Measured fidelity bounds for the standalone timing engine (VERDICT r3
item 4: "the column-space claim must carry a measured number").

PINT itself is not installable in this environment (and its DE440
ephemeris files are absent), so a frozen PINT fixture cannot be
generated here. These tests pin the engine with what IS independently
measurable:

- the time-scale chain against published anchors (leap-second table,
  GMST at J2000, TDB-TT annual extrema),
- the observatory geometry against the real NANOGrav observing
  schedule (Arecibo's zenith-limited dish physically cannot observe
  beyond ~+-20 deg hour angle for B1855+09 — GMST/ITRF/precession all
  have to be right for the implied hour angles to land in that window),
- parameter recovery on the real B1855+09 design (7,758 real TOAs,
  real frequencies/flags, 166 active columns): perturb 21 parameters
  across every family by +3 of PINT's own published par-file
  uncertainties, refit with this engine, and require recovery to a
  small fraction of sigma.
"""
import numpy as np
import pytest

from pta_replicator_tpu.io.par import _parse_float
from pta_replicator_tpu.timing.time_scales import (
    gmst_rad,
    site_itrf_m,
    tai_minus_utc,
    tdb_minus_tt,
    tdb_minus_utc,
)

PAR = "/root/reference/test_partim/par/B1855+09.par"
TIM = "/root/reference/test_partim/tim/B1855+09.tim"


def _have_b1855():
    import os

    return os.path.isfile(PAR) and os.path.isfile(TIM)


def test_leap_second_table():
    # published TAI-UTC anchors
    assert tai_minus_utc(41317.0) == 10.0          # 1972-01-01
    assert tai_minus_utc(50000.0) == 29.0          # 1995-10-10
    assert tai_minus_utc(53735.9) == 32.0          # day before 2006-01-01
    assert tai_minus_utc(53736.0) == 33.0          # 2006-01-01
    assert tai_minus_utc(58000.0) == 37.0          # post-2017, current
    assert tai_minus_utc(41000.0) == 0.0           # pre-table


def test_gmst_published_anchors():
    # GMST at 2000-01-01 12:00 UT (J2000.0): 18.697374558 h
    h = gmst_rad(51544.5) * 12.0 / np.pi
    assert h == pytest.approx(18.697374558, abs=1e-6)
    # GMST at 2000-01-01 00:00 UT: 6h 39m 52.2687s (Astronomical Almanac)
    h0 = gmst_rad(51544.0) * 12.0 / np.pi
    assert h0 == pytest.approx(6.0 + 39.0 / 60.0 + 52.2687 / 3600.0,
                               abs=1e-5)


def test_tdb_minus_tt_annual_shape():
    """The Fairhead series must show the known ~1.66 ms annual term:
    extrema near +-1.66 ms, zero crossings near perihelion (early Jan) /
    aphelion (early Jul)."""
    days = np.arange(58849.0, 58849.0 + 366.0)  # calendar year 2020
    v = tdb_minus_tt(days)
    assert np.max(v) == pytest.approx(1.66e-3, rel=0.03)
    assert np.min(v) == pytest.approx(-1.66e-3, rel=0.03)
    # maximum occurs ~91 days after perihelion (g ~ 90 deg, early April)
    tmax = days[np.argmax(v)]
    apr1 = 58940.0  # 2020-04-01
    assert abs(tmax - apr1) < 15.0
    # total UTC->TDB offset in 2020 is 37 + 32.184 +- periodic
    tot = tdb_minus_utc(days)
    assert np.all(np.abs(tot - 69.184) < 2e-3)


def test_ecliptic_conversion_roundtrip():
    from pta_replicator_tpu.ops.coords import (
        ecliptic_to_equatorial,
        equatorial_to_ecliptic,
        equatorial_to_ecliptic_tangent,
    )

    rng = np.random.default_rng(0)
    for _ in range(20):
        lon = float(rng.uniform(0, 360))
        lat = float(rng.uniform(-80, 80))
        for epoch in ("2000", "1950"):
            ra, dec = ecliptic_to_equatorial(lon, lat, epoch=epoch)
            lon2, lat2 = equatorial_to_ecliptic(ra, dec, epoch=epoch)
            assert lon2 == pytest.approx(lon, abs=1e-9)
            assert lat2 == pytest.approx(lat, abs=1e-9)
    # tangent-plane rotation is orthonormal with det +1 (pure rotation)
    # and, per epoch, matches the finite-difference Jacobian of the
    # point conversion itself (regression: the 1950 case used to mix a
    # B1950 position with the J2000 ecliptic pole — a ~0.6 deg skew)
    for epoch in ("2000", "1950"):
        ra, dec = 1.1, 0.3
        R = equatorial_to_ecliptic_tangent(ra, dec, epoch=epoch)
        assert np.allclose(R @ R.T, np.eye(2), atol=1e-12)
        assert np.linalg.det(R) == pytest.approx(1.0, abs=1e-12)
        eps = 1e-7

        def lonstar_lat(ra_, dec_):
            lon_, lat_ = equatorial_to_ecliptic(ra_, dec_, epoch=epoch)
            return np.deg2rad(lon_), np.deg2rad(lat_)

        lon0, lat0 = lonstar_lat(ra, dec)
        clat = np.cos(lat0)
        J = np.empty((2, 2))
        for j, (dra, ddec) in enumerate(
            [(eps / np.cos(dec), 0.0), (0.0, eps)]
        ):
            lon1, lat1 = lonstar_lat(ra + dra, dec + ddec)
            dlon = (lon1 - lon0 + np.pi) % (2 * np.pi) - np.pi
            J[0, j] = clat * dlon / eps
            J[1, j] = (lat1 - lat0) / eps
        np.testing.assert_allclose(R, J, atol=1e-5)


@pytest.mark.skipif(not _have_b1855(), reason="B1855+09 fixture absent")
def test_arecibo_hour_angles_physical():
    """External geometry anchor: the hour angles implied by our GMST +
    Arecibo ITRF coordinates at the real observing epochs must land in
    the dish's physical zenith window (the 305 m dish tracks only
    ~+-1.7 h around transit). A wrong GMST, site vector, or frame
    rotation scatters them over +-180 deg."""
    from pta_replicator_tpu import load_pulsar

    psr = load_pulsar(PAR, TIM)
    mjds = psr.toas.get_mjds().astype(np.float64)
    g = gmst_rad(mjds)
    site = site_itrf_m("arecibo")
    lon = np.arctan2(site[1], site[0])  # ITRF east longitude
    ha = (g + lon - psr.model.ra_rad + np.pi) % (2 * np.pi) - np.pi
    ha_deg = np.rad2deg(ha)
    assert np.max(np.abs(ha_deg)) < 26.0
    assert np.std(ha_deg) < 10.0


@pytest.mark.skipif(not _have_b1855(), reason="B1855+09 fixture absent")
def test_topocentric_term_magnitude():
    """Arecibo's geocentric delay for B1855+09 is ~-21 ms (R_earth/c
    projected on the source) with a few-hundred-us hour-angle spread."""
    from pta_replicator_tpu import load_pulsar
    from pta_replicator_tpu.timing.components import AU_S
    from pta_replicator_tpu.timing.time_scales import (
        observatory_position_au,
    )

    psr = load_pulsar(PAR, TIM)
    mjds = psr.toas.get_mjds().astype(np.float64)
    r = observatory_position_au(mjds, psr.toas.observatories)
    ca, sa = np.cos(psr.model.ra_rad), np.sin(psr.model.ra_rad)
    cd, sd = np.cos(psr.model.dec_rad), np.sin(psr.model.dec_rad)
    topo = -(r @ np.array([ca * cd, sa * cd, sd])) * AU_S
    assert -0.0215 < topo.mean() < -0.019
    assert 5e-5 < topo.std() < 5e-4
    # unknown codes fall back to the geocenter
    r0 = observatory_position_au(mjds[:4], ["AXIS"] * 4)
    assert np.all(r0 == 0.0)


@pytest.mark.skipif(not _have_b1855(), reason="B1855+09 fixture absent")
def test_b1855_parameter_recovery_three_sigma():
    """The headline measured bound: on the real B1855+09 design (7,758
    TOAs, 166 active columns), perturb 21 parameters spanning spin,
    ecliptic astrometry (position, PM, PX), FD, binary (ELL1 incl.
    Shapiro M2/SINI), DMX, and the flag-matched JUMP by +3 of PINT's
    published uncertainties; the damped iterated WLS refit must recover
    every one to <0.1 sigma (measured: worst ~0.05 sigma, median ~3e-4)
    with sub-ns post-fit residuals."""
    from pta_replicator_tpu import load_pulsar, make_ideal
    from pta_replicator_tpu.timing.model import TimingModel

    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)  # TOAs now encode the unperturbed model exactly

    def sigma(key):
        t = psr.par.params.get(key)
        if t and len(t) >= 3:
            try:
                return _parse_float(t[2])
            except ValueError:
                return None

    perturb = [
        "F0", "F1", "ELONG", "ELAT", "PMELONG", "PMELAT", "PX",
        "FD1", "FD2", "PB", "A1", "EPS1", "EPS2", "TASC", "M2", "SINI",
        "DMX_0003", "DMX_0050", "DMX_0100", "DMX_0140",
    ]
    applied = {}
    for k in perturb:
        s = sigma(k)
        assert s is not None, f"no published uncertainty for {k}"
        v = _parse_float(psr.par.params[k][0])
        psr.par.set_param(k, v + 3 * s)
        applied[k] = (v, s)
    jv = psr.par.jumps[0][2]
    js = 4.083841525492636e-06  # the par's published JUMP uncertainty
    psr.par.set_jump(0, jv + 3 * js)
    applied["JUMP1"] = (jv, js)

    psr.model = TimingModel.from_par(psr.par)
    psr.update_residuals()
    pre = float(psr.residuals.resids_value.std())
    assert pre > 5e-6  # the perturbation is visible (~17 us RMS)

    psr.fit(fitter="wls", niter=4)
    post = float(psr.residuals.resids_value.std())
    assert post < 1e-9, f"post-fit rms {post*1e9:.2f} ns"

    errs = {}
    for k, (v0, s) in applied.items():
        vf = (psr.par.jumps[0][2] if k == "JUMP1"
              else _parse_float(psr.par.params[k][0]))
        errs[k] = abs(vf - v0) / s
    worst = max(errs, key=errs.get)
    assert errs[worst] < 0.1, f"{worst} recovered at {errs[worst]:.3f} sigma"
    assert np.median(list(errs.values())) < 0.01


def test_wave_model_roundtrip_and_recovery(tmp_path):
    """tempo2/PINT WAVE model: ensure_waves declares the basis, an
    injected harmonic signal in the TOAs is recovered into the WAVEk
    amplitudes by the fit, and the fitted par round-trips through
    write/load with the amplitudes intact."""
    from pta_replicator_tpu import load_pulsar, make_ideal
    from pta_replicator_tpu.timing.model import TimingModel

    par = "/root/reference/test_partim_small/par/JPSR00.par"
    tim = "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim"
    psr = load_pulsar(par, tim)
    make_ideal(psr)
    mjds = psr.toas.get_mjds().astype(np.float64)
    span = float(mjds.max() - mjds.min())
    om = 2 * np.pi / (1.05 * span)
    psr.par.ensure_waves(5, om=om, epoch=float(mjds.min()))
    psr.model = TimingModel.from_par(psr.par)

    a3, b3 = 3e-6, -2e-6
    ph = 3 * om * (mjds - mjds.min())
    psr.inject("wave_signal", {}, a3 * np.sin(ph) + b3 * np.cos(ph))
    assert psr.residuals.resids_value.std() > 1e-6

    psr.fit(fitter="wls", niter=2)
    w = psr.par.waves
    assert w[2][0] == pytest.approx(a3, rel=1e-3)
    assert w[2][1] == pytest.approx(b3, rel=1e-3)
    assert psr.residuals.resids_value.std() < 1e-8
    # other harmonics stay ~zero (the basis is orthogonal on this span)
    assert abs(w[0][0]) < 0.05 * abs(a3)

    # round-trip: fitted WAVE lines persist through write_partim
    psr.write_partim(str(tmp_path / "w.par"), str(tmp_path / "w.tim"))
    back = load_pulsar(str(tmp_path / "w.par"), str(tmp_path / "w.tim"))
    assert back.par.wave_om == pytest.approx(om)
    assert back.par.waves[2][0] == pytest.approx(w[2][0])
    assert back.model.waves[2][1] == pytest.approx(w[2][1])


@pytest.mark.skipif(not _have_b1855(), reason="B1855+09 fixture absent")
def test_solar_shapiro_magnitude_and_shape():
    """The solar Shapiro term is a us-scale annual signature peaking
    when the line of sight passes closest to the Sun."""
    import dataclasses

    from pta_replicator_tpu import load_pulsar
    from pta_replicator_tpu.timing.model import phase_residuals

    psr = load_pulsar(PAR, TIM)
    toas = psr.toas
    no_sun = dataclasses.replace(psr.model, include_solar_shapiro=False)
    with_sun = psr.residuals.time_resids
    without = phase_residuals(
        no_sun, toas.mjd, toas.errors_s, freqs_mhz=toas.freqs_mhz,
        flags=toas.flags, observatories=toas.observatories,
    )
    sig = with_sun - without
    # mean-subtracted signature: few-us RMS, annual periodicity
    assert 1e-6 < sig.std() < 3e-5
    mjds = toas.get_mjds().astype(np.float64)
    yr_phase = 2 * np.pi * mjds / 365.25
    c = np.column_stack([np.sin(yr_phase), np.cos(yr_phase),
                         np.sin(2 * yr_phase), np.cos(2 * yr_phase)])
    amp, *_ = np.linalg.lstsq(c, sig - sig.mean(), rcond=None)
    model = c @ amp
    # the annual+semiannual harmonics carry most of the variance
    assert np.var(sig - sig.mean() - model) < 0.5 * np.var(sig)


def test_solar_wind_dispersion_chromatic():
    """NE_SW > 0 adds a chromatic (1/f^2) delay that grows toward small
    solar elongation; NE_SW = 0 (all reference fixtures) is a no-op."""
    import dataclasses

    from pta_replicator_tpu import load_pulsar
    from pta_replicator_tpu.timing.model import phase_residuals

    par = "/root/reference/test_partim_small/par/JPSR00.par"
    tim = "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim"
    psr = load_pulsar(par, tim)
    toas = psr.toas
    base = psr.residuals.time_resids
    m_sw = dataclasses.replace(psr.model, ne_sw=10.0)
    r_sw = phase_residuals(
        m_sw, toas.mjd, toas.errors_s, freqs_mhz=toas.freqs_mhz,
        flags=toas.flags, observatories=toas.observatories,
    )
    sig = r_sw - base
    assert sig.std() > 1e-8  # visible at NE_SW=10
    # scales as 1/f^2: recompute at doubled frequency
    toas2 = toas
    f2 = toas.freqs_mhz * 2.0
    r_sw2 = phase_residuals(
        m_sw, toas2.mjd, toas2.errors_s, freqs_mhz=f2,
        flags=toas2.flags, observatories=toas2.observatories,
    )
    base2 = phase_residuals(
        psr.model, toas2.mjd, toas2.errors_s, freqs_mhz=f2,
        flags=toas2.flags, observatories=toas2.observatories,
    )
    sig2 = r_sw2 - base2
    ratio = np.std(sig2) / np.std(sig)
    assert ratio == pytest.approx(0.25, rel=0.15)


def test_dd_binary_parameter_recovery(tmp_path):
    """BT/DD Kepler-solve branch (the fidelity headline covers ELL1):
    build a synthetic eccentric DD binary on fabricated TOAs, perturb
    PB/A1/T0/OM/ECC/M2/SINI, and require the numerical-Jacobian refit to
    recover each to a small fraction of the injected offset."""
    from pta_replicator_tpu import load_pulsar, make_ideal, simulate_pulsar
    from pta_replicator_tpu.timing.model import TimingModel

    base = open(
        "/root/reference/test_partim_small/par/JPSR00.par"
    ).read()
    par_path = tmp_path / "dd.par"
    par_path.write_text(
        base
        + "\nBINARY DD\nPB 67.825\nA1 32.342\nT0 53100.5\nOM 110.3\n"
        + "ECC 0.274\nM2 0.30\nSINI 0.93\nGAMMA 0.004\n"
    )
    mjds = np.linspace(53000.0, 53000.0 + 12 * 365.25, 3000)
    psr = simulate_pulsar(str(par_path), mjds, 0.5)
    make_ideal(psr)

    # perturbations sized like realistic fit uncertainties
    deltas = {
        "PB": 3e-7, "A1": 2e-5, "T0": 4e-5, "OM": 3e-4,
        "ECC": 3e-6, "M2": 0.02, "SINI": 0.004,
    }
    truth = {}
    for k, dv in deltas.items():
        v = float(psr.par.params[k][0])
        truth[k] = v
        psr.par.set_param(k, v + dv)
    psr.model = TimingModel.from_par(psr.par)
    psr.update_residuals()
    assert psr.residuals.resids_value.std() > 1e-7

    psr.fit(fitter="wls", niter=6)
    assert psr.residuals.resids_value.std() < 5e-9
    for k, dv in deltas.items():
        vf = float(psr.par.params[k][0])
        # recovered to <10% of the injected offset (M2/SINI are nearly
        # degenerate at moderate inclination: <35%)
        tol = 0.35 if k in ("M2", "SINI") else 0.10
        assert abs(vf - truth[k]) < tol * abs(dv), (
            f"{k}: injected {dv}, residual offset {vf - truth[k]}"
        )


def test_solar_wind_closed_form_vs_numerical_integration():
    """The (pi - psi)/(|r| sin psi) elongation factor must equal the
    brute-force line-of-sight integral of n_e(r) = n0 (AU/r)^2 —
    an independent check of the closed form (tempo2/PINT convention)."""
    rng = np.random.default_rng(8)
    for _ in range(12):
        r_e = rng.uniform(0.98, 1.02)        # Earth-Sun distance [AU]
        psi = rng.uniform(0.05, np.pi - 0.05)  # elongation
        closed = (np.pi - psi) / (r_e * np.sin(psi))
        # numeric: Earth at origin, Sun at distance r_e, LOS at angle
        # psi from the Sun direction; r(l)^2 = r_e^2 + l^2 - 2 r_e l cos
        lmax = 2000.0
        l = np.linspace(0.0, lmax, 2_000_001)
        r2 = r_e**2 + l**2 - 2.0 * r_e * l * np.cos(psi)
        numeric = np.trapezoid(1.0 / r2, l)
        # the finite upper limit truncates ~1/lmax of the integral
        assert closed == pytest.approx(numeric, rel=2e-3), (r_e, psi)


def test_wls_uncertainty_matches_analytic():
    """wls_fit's return_cov diagonal must equal the closed-form
    (M^T N^-1 M)^-1 on a small conditioned problem, and scale linearly
    with the TOA errors."""
    from pta_replicator_tpu.timing.fit import wls_fit

    rng = np.random.default_rng(2)
    n = 200
    t = np.linspace(-1.0, 1.0, n)
    M = np.stack([np.ones(n), t, t**2], axis=-1)
    sigma = rng.uniform(0.5, 2.0, n)
    r = rng.standard_normal(n) * sigma
    p, post, pcov = wls_fit(r, sigma, M, return_cov=True)
    A = M.T @ (M / sigma[:, None] ** 2)
    np.testing.assert_allclose(pcov, np.linalg.inv(A), rtol=1e-9)
    _, _, pcov2 = wls_fit(r, 3.0 * sigma, M, return_cov=True)
    np.testing.assert_allclose(pcov2, 9.0 * pcov, rtol=1e-9)


@pytest.mark.skipif(not _have_b1855(), reason="B1855+09 fixture absent")
def test_fit_uncertainties_match_published_b1855():
    """VERDICT r4 item 5: fit() must report per-parameter uncertainties
    ((M^T C^-1 M)^-1 diagonal) and persist them to the par's error
    columns. Anchor: a GLS fit weighted by B1855+09's own NG15 noise
    model (per-backend EFAC/EQUAD/ECORR + red noise) must land within a
    factor ~2 of PINT's published par-file sigmas for well-constrained
    parameters — and within 25% for the sharp short-timescale ones
    (A1, DMX, FD1), where the red-noise convention details PINT and this
    engine differ on (basis span, mode count) barely matter.

    Measured ratios at introduction (ours/published): F0 2.42, F1 1.99,
    ELONG 1.20, PMELONG 1.07, PX 1.25, A1 0.99, PB 1.02, M2 0.90,
    SINI 0.83, TASC 1.18, DMX 0.99, FD1 0.99.
    """
    import jax.numpy as jnp

    import pta_replicator_tpu as ptr
    from pta_replicator_tpu.io.noise_dict import parse_noise_dict
    from pta_replicator_tpu.io.par import read_par
    from pta_replicator_tpu.models.batched import Recipe

    pub = read_par(PAR)
    nd = parse_noise_dict(
        "/root/reference/noise_dicts/ng15_dict.json"
    )["B1855+09"]

    psr = ptr.load_pulsar(PAR, TIM)
    ptr.make_ideal(psr)
    ptr.add_measurement_noise(psr, efac=1.0, seed=5)

    def tab(vals, default):
        return jnp.asarray([[default if v is None else v for v in vals]])

    recipe = Recipe(
        efac=tab(nd["efac"], 1.0),
        log10_equad=tab(nd["log10_t2equad"], -10.0),
        log10_ecorr=tab(nd["log10_ecorr"], -10.0),
        rn_log10_amplitude=jnp.asarray([nd["red_noise_log10_A"]]),
        rn_gamma=jnp.asarray([nd["red_noise_gamma"]]),
    )
    psr.fit(fitter="gls", recipe=recipe, psr_index=0,
            backend_names=nd["backends"], niter=1)

    assert len(psr.fit_uncertainties) > 150  # every active column

    loose = ["F0", "F1", "ELONG", "ELAT", "PMELONG", "PMELAT", "PX",
             "PB", "M2", "SINI", "TASC"]
    sharp = ["A1", "DMX_0001", "DMX_0002", "FD1"]
    for key in loose + sharp:
        pe = pub.param_error(key)
        oe = psr.par.param_error(key)
        assert pe and oe, key
        lo, hi = (0.8, 1.25) if key in sharp else (0.4, 2.5)
        assert lo < oe / pe < hi, (key, oe / pe)
    # the round-tripped par carries the new sigmas (write_partim surface)
    import tempfile, os

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "fitted.par")
        psr.par.write(path)
        re = read_par(path)
        assert re.param_error("A1") == pytest.approx(
            psr.par.param_error("A1")
        )
        assert re.param_error("F0") == pytest.approx(
            psr.par.param_error("F0")
        )
