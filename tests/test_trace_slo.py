"""Causal tracing + SLO engine (PR 14): TraceContext propagation and
determinism, request-trace stitching under races (concurrent submits,
stop()-drain, postmortem flush of open traces), chunk-trace identity
across retry/resume, the SLO grammar/budget/burn/breach math, the
/slo + /readyz surface, timeline trace flows, and the bench-diff
direction contract for the TRACE series."""
import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pta_replicator_tpu import likelihood as lk
from pta_replicator_tpu import obs
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.models.batched import Recipe, realize
from pta_replicator_tpu.obs import names, slo as slo_mod
from pta_replicator_tpu.obs import trace as trace_mod
from pta_replicator_tpu.obs.trace import (
    TRACER,
    Tracer,
    adopt,
    carry,
    chunk_trace_context,
    deterministic_trace_context,
    new_trace_context,
)


@pytest.fixture(scope="module")
def setup():
    batch = synthetic_batch(npsr=4, ntoa=96, seed=7)
    recipe = Recipe(
        efac=jnp.asarray(1.1),
        rn_log10_amplitude=jnp.asarray(-13.5),
        rn_gamma=jnp.asarray(4.0),
        rn_nmodes=8,
    )
    bank = np.asarray(
        realize(jax.random.PRNGKey(0), batch, recipe, nreal=6)
    )
    return batch, recipe, bank


def _traced_spans(tracer=None):
    out = {}
    for rec in (tracer or TRACER).events():
        if rec.get("type") == "span" and "trace_id" in rec:
            out.setdefault(rec["trace_id"], []).append(rec)
    return out


# ----------------------------------------------------- trace contexts

def test_span_records_carry_trace_fields_and_nest():
    tracer = Tracer()
    ctx = new_trace_context()
    with adopt(ctx):
        with tracer.span("outer"):
            assert carry().trace_id == ctx.trace_id
            with tracer.span("inner"):
                pass
        tracer.record_span("synth", time.time(), 0.001)
    recs = {r["name"]: r for r in tracer.events()}
    outer, inner, synth = recs["outer"], recs["inner"], recs["synth"]
    assert outer["trace_id"] == inner["trace_id"] == ctx.trace_id
    assert synth["trace_id"] == ctx.trace_id
    # the chain: outer's parent is the root span id, inner's parent is
    # outer's own span id (causal nesting, not just shared trace)
    assert outer["parent_id"] == ctx.span_id
    assert inner["parent_id"] == outer["span_id"]
    assert synth["parent_id"] == ctx.span_id
    assert len(outer["trace_id"]) == 32 and len(outer["span_id"]) == 16
    # untraced spans carry no trace fields
    with tracer.span("plain"):
        pass
    plain = [r for r in tracer.events() if r["name"] == "plain"][0]
    assert "trace_id" not in plain and "span_id" not in plain


def test_links_and_event_stamping():
    tracer = Tracer()
    ctx = new_trace_context()
    with tracer.span("fanin", links=[ctx.trace_id, "other"]):
        pass
    rec = tracer.events()[-1]
    assert rec["links"] == [ctx.trace_id, "other"]
    with adopt(ctx):
        tracer.event("probe", k=1)
    ev = tracer.events()[-1]
    assert ev["trace_id"] == ctx.trace_id
    assert ev["parent_id"] == ctx.span_id


def test_deterministic_chunk_contexts():
    a = chunk_trace_context("ckpt.npz", 3)
    b = chunk_trace_context("ckpt.npz", 3)
    c = chunk_trace_context("ckpt.npz", 4)
    d = chunk_trace_context("other.npz", 3)
    assert a == b
    assert len({a.trace_id, c.trace_id, d.trace_id}) == 3
    assert deterministic_trace_context("x", 1) == \
        deterministic_trace_context("x", 1)


def test_trace_id_stream_resets_per_capture_epoch():
    trace_mod.reset_trace_ids()
    first = [new_trace_context() for _ in range(3)]
    trace_mod.reset_trace_ids()
    second = [new_trace_context() for _ in range(3)]
    # same epoch-relative allocation order after a reset would collide
    # across epochs if the epoch were not folded into the digest
    assert [c.trace_id for c in first] != [c.trace_id for c in second]
    # within one epoch the stream is unique
    assert len({c.trace_id for c in second}) == 3


def test_adopt_none_is_a_shield():
    ctx = new_trace_context()
    with adopt(ctx):
        with adopt(None):
            assert carry() is None
        assert carry() == ctx


# -------------------------------------------- request-trace stitching

def test_concurrent_submits_get_unique_trace_ids(setup):
    """Hammer: submits racing from many threads never share a
    trace_id (id allocation is atomic under the GIL)."""
    batch, recipe, bank = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=8, max_delay_s=0.001,
    )
    futs = []
    lock = threading.Lock()

    def client(k):
        f = server.submit(rn_log10_amplitude=-13.5 - 1e-3 * k)
        with lock:
            futs.append(f)

    with server:
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futs:
            f.result(timeout=60)
    tids = [f.trace_id for f in futs]
    assert len(set(tids)) == 32


def test_stop_drained_futures_still_close_their_traces(setup):
    """A request served by the stop() drain still gets queue-wait +
    resolution spans and leaves the open-request registry."""
    obs.reset_all()
    batch, recipe, bank = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=4, max_delay_s=10.0,
    )
    server.start()
    futs = [server.submit(rn_log10_amplitude=-13.5 + 0.01 * i)
            for i in range(5)]
    assert trace_mod.open_request_count() == 5
    server.stop()
    for f in futs:
        assert f.exception() is None
    spans = _traced_spans()
    for f in futs:
        got = [r["name"] for r in spans[f.trace_id]]
        assert names.SPAN_LIKELIHOOD_SUBMIT in got
        assert names.SPAN_LIKELIHOOD_QUEUE_WAIT in got
        assert names.SPAN_LIKELIHOOD_RESOLVE in got
    assert trace_mod.open_request_count() == 0
    # the coalesced batch span links every request it served
    linked = set()
    for rec in TRACER.events():
        if rec.get("name") == names.SPAN_LIKELIHOOD_BATCH:
            linked.update(rec.get("links") or [])
    assert {f.trace_id for f in futs} <= linked
    obs.reset_all()


def test_rejection_and_expiry_stamp_trace_ids(setup):
    """ServerSaturated/DeadlineExpired messages carry the trace id, the
    matching per-request events are stamped, and expired requests leave
    the open-request registry."""
    obs.reset_all()
    batch, recipe, bank = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=1, max_delay_s=0.001,
        max_queue=1, request_deadline_s=0.02,
    )
    entered = threading.Event()
    release = threading.Event()

    def gated_engine(theta, *a, **k):
        entered.set()
        release.wait(30.0)
        return np.zeros((theta.shape[0], bank.shape[0]))

    server._engine = gated_engine
    with server:
        first = server.submit(rn_log10_amplitude=-13.5)
        assert entered.wait(10.0)
        stale = server.submit(rn_log10_amplitude=-13.6)
        with pytest.raises(lk.ServerSaturated) as exc:
            server.submit(rn_log10_amplitude=-13.7)
        assert "(trace " in str(exc.value)
        rejected_tid = str(exc.value).rsplit("(trace ", 1)[1].rstrip(")")
        time.sleep(0.1)  # the queued request expires
        release.set()
    assert first.exception() is None
    with pytest.raises(lk.DeadlineExpired, match=stale.trace_id):
        stale.result(timeout=0)
    events = {
        (r["name"], r.get("trace_id"))
        for r in TRACER.events() if r.get("type") == "event"
    }
    assert (names.EVENT_LIKELIHOOD_REJECTED, rejected_tid) in events
    assert (names.EVENT_LIKELIHOOD_DEADLINE_EXPIRED,
            stale.trace_id) in events
    # even the rejected request left a greppable submit span
    assert rejected_tid in _traced_spans()
    assert trace_mod.open_request_count() == 0
    obs.reset_all()


def test_postmortem_flushes_open_request_traces(tmp_path, setup):
    """A postmortem written while requests are in flight lists them
    under open_traces (the black box names what died with the run)."""
    obs.reset_all()
    batch, recipe, bank = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=1, max_delay_s=0.001,
    )
    entered = threading.Event()
    release = threading.Event()

    def gated_engine(theta, *a, **k):
        entered.set()
        release.wait(30.0)
        return np.zeros((theta.shape[0], bank.shape[0]))

    server._engine = gated_engine
    from pta_replicator_tpu.obs.flightrec import FlightRecorder

    rec = FlightRecorder(str(tmp_path), stall_timeout_s=None)
    with server:
        server.submit(rn_log10_amplitude=-13.5)
        assert entered.wait(10.0)
        queued = server.submit(rn_log10_amplitude=-13.6)
        pm_path = rec.write_postmortem("test-flush")
        release.set()
    queued.result(timeout=30)
    pm = json.loads(open(pm_path).read())
    open_ids = {t["trace_id"] for t in pm["open_traces"]}
    assert queued.trace_id in open_ids
    assert all(
        t.get("kind") == "likelihood_request" for t in pm["open_traces"]
    )
    obs.reset_all()


# -------------------------------------------------- chunk trace identity

def test_sweep_chunk_traces_identical_across_depths_and_resume(
        tmp_path, setup):
    """Chunk trace ids derive from (checkpoint path, chunk): the sync
    loop, the pipelined executor, and a resumed sweep all stitch onto
    the same per-chunk traces."""
    obs.reset_all()
    batch, recipe, _bank = setup
    key = jax.random.PRNGKey(4)

    ck1 = str(tmp_path / "a.npz")
    sweep_kwargs = dict(nreal=8, chunk=4, reduce_fn=None)
    from pta_replicator_tpu.utils.sweep import sweep

    sweep(key, batch, recipe, checkpoint_path=ck1, pipeline_depth=1,
          **sweep_kwargs)
    depth1 = _traced_spans()
    obs.reset_all()
    ck2 = str(tmp_path / "b.npz")
    sweep(key, batch, recipe, checkpoint_path=ck2, pipeline_depth=2,
          **sweep_kwargs)
    depth2 = _traced_spans()
    # same chunk + same path => same trace id, at any depth
    assert set(depth1) != set(depth2)  # different paths differ
    assert chunk_trace_context(ck1, 0).trace_id in depth1
    assert chunk_trace_context(ck2, 0).trace_id in depth2
    for i in (0, 1):
        tid = chunk_trace_context(ck2, i).trace_id
        got = {r["name"] for r in depth2[tid]}
        assert {names.SPAN_DISPATCH, names.SPAN_DRAIN,
                names.SPAN_IO_WRITE} <= got
    obs.reset_all()


def test_sweep_retry_joins_the_same_chunk_trace(tmp_path, setup):
    """A supervised retry resumes into the SAME per-chunk trace: the
    retried chunk shows two dispatch attempts plus a trace-stamped
    faults.retry event (the multi-attempt trace contract)."""
    from pta_replicator_tpu.faults import inject
    from pta_replicator_tpu.faults.retry import RetryPolicy
    from pta_replicator_tpu.utils.sweep import sweep

    obs.reset_all()
    batch, recipe, _bank = setup
    ck = str(tmp_path / "retry.npz")
    pol = RetryPolicy(max_attempts=4, base_delay_s=0.05, max_delay_s=0.2)
    with inject.armed("drain:raise@chunk=1", seed=0):
        sweep(jax.random.PRNGKey(5), batch, recipe, nreal=8, chunk=4,
              checkpoint_path=ck, reduce_fn=None, chunk_retries=2,
              retry_policy=pol)
    tid = chunk_trace_context(ck, 1).trace_id
    spans = _traced_spans()[tid]
    assert [r["name"] for r in spans].count(names.SPAN_DISPATCH) >= 2
    retry_evs = [
        r for r in TRACER.events()
        if r.get("type") == "event"
        and r.get("name") == names.EVENT_FAULT_RETRY
    ]
    assert any(r.get("trace_id") == tid for r in retry_evs)
    obs.reset_all()


def test_prefetch_workers_adopt_callers_trace(setup):
    """The carry()/adopt() handoff: cw_stream_stage spans recorded on
    the prefetch worker thread stitch onto the consumer's live trace."""
    from pta_replicator_tpu.parallel.prefetch import prefetch_to_device

    obs.reset_all()
    ctx = new_trace_context()
    with adopt(ctx):
        tiles = [np.ones(4), np.ones(4)]
        out = list(prefetch_to_device(iter(tiles), depth=2,
                                      place=lambda t: t))
    assert len(out) == 2
    staged = [
        r for r in TRACER.events()
        if r.get("name") == names.SPAN_CW_STREAM_STAGE
    ]
    assert staged and all(
        r.get("trace_id") == ctx.trace_id for r in staged
    )
    obs.reset_all()


# --------------------------------------------------------- SLO engine

def test_slo_grammar_parses_and_rejects():
    obj = slo_mod.parse_objective(
        "serve=likelihood_batch:p99_ms<=60@99.9%"
    )
    assert obj.kind == "latency" and obj.span == "likelihood_batch"
    assert obj.threshold_s == pytest.approx(0.060)
    assert obj.target == pytest.approx(0.999)
    assert obj.spec_str() == "serve=likelihood_batch:p99_ms<=60@99.9%"
    avail = slo_mod.parse_objective(
        "admit=err(likelihood.deadline_expired/likelihood.requests)@99%"
    )
    assert avail.kind == "availability"
    assert avail.bad_metric == "likelihood.deadline_expired"
    for bad in (
        "noname@99%", "x=foo@99%", "x=span:p99_ms<=60",
        "x=span:p99_ms<=60@101%", "x=span:p99_ms<=60@0%",
        "x=span:p99_ms<=abc@99%",
    ):
        with pytest.raises(slo_mod.SLOSpecError):
            slo_mod.parse_objective(bad)
    # labeled metric instances are refused at parse time: _metric_total
    # sums families by bare name, so a label suffix would parse and
    # then silently score nothing
    with pytest.raises(slo_mod.SLOSpecError, match="labeled"):
        slo_mod.parse_objective(
            "x=err(faults.injected{site=drain}/faults.injected)@99%"
        )
    with pytest.raises(slo_mod.SLOSpecError, match="duplicate"):
        slo_mod.parse_objectives(
            "a=s:p99_ms<=1@99%;a=t:p99_ms<=1@99%"
        )


def test_slo_latency_budget_and_burn_math():
    from pta_replicator_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    engine = slo_mod.SLOEngine(
        "lat=probe_span:p99_ms<=10@90%", registry=reg
    )
    # 8 good + 2 bad of 10 events: bad_frac 0.2, allowance 0.1 ->
    # burn 2.0, budget remaining 1 - 2.0 = -1.0
    for wall in [0.001] * 8 + [0.5] * 2:
        engine.observe_span(
            {"type": "span", "name": "probe_span", "wall_s": wall}
        )
    st = engine.status()["objectives"]["lat"]
    assert st["events"] == 10 and st["bad"] == 2
    assert st["sli"] == pytest.approx(0.8)
    assert st["burn_rate_slow"] == pytest.approx(2.0)
    assert st["error_budget_remaining"] == pytest.approx(-1.0)
    # 2/10 bad at 10% allowance = 2x burn: under the 14.4 page point
    assert not st["breach"]


def test_slo_breach_fires_once_per_episode():
    from pta_replicator_tpu.obs.metrics import MetricsRegistry

    obs.reset_all()
    reg = MetricsRegistry()
    engine = slo_mod.SLOEngine("lat=probe_span:p99_ms<=10@99%",
                               registry=reg)
    for _ in range(20):
        engine.observe_span(
            {"type": "span", "name": "probe_span", "wall_s": 0.5}
        )
    engine.sample()
    engine.sample()  # still breaching: no second event
    breaches = [
        r for r in TRACER.events()
        if r.get("type") == "event"
        and r.get("name") == names.EVENT_SLO_BREACH
    ]
    assert len(breaches) == 1
    assert breaches[0]["attrs"]["objective"] == "lat"
    st = engine.status()["objectives"]["lat"]
    assert st["breach"] and st["breaches"] == 1
    gauges = {
        (m.name, tuple(m.labels)): m.value for m in reg.metrics()
    }
    assert gauges[
        (names.SLO_BURN_RATE_FAST, (("objective", "lat"),))
    ] == pytest.approx(100.0)
    obs.reset_all()


def test_slo_availability_clamps_disjoint_counters():
    from pta_replicator_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    engine = slo_mod.SLOEngine("a=err(bad.count/total.count)@99%",
                               registry=reg)
    engine.sample()  # baseline
    reg.counter("total.count").inc(10)
    reg.counter("bad.count").inc(3)
    engine.sample()
    st = engine.status()["objectives"]["a"]
    assert st["events"] == 10 and st["bad"] == 3
    assert st["sli"] == pytest.approx(0.7)
    # disjoint misuse (bad > total) clamps to an all-bad window rather
    # than a negative SLI
    reg.counter("bad.count").inc(50)
    engine.sample()
    st = engine.status()["objectives"]["a"]
    assert 0.0 <= st["sli"] <= 1.0


def test_slo_engine_inert_without_objectives():
    engine = slo_mod.SLOEngine()
    assert not engine.armed
    engine.observe_span({"type": "span", "name": "x", "wall_s": 1.0})
    engine.sample()
    assert engine.status()["objectives"] == {}
    assert engine.heartbeat_block() == {"objectives": {}, "breached": []}


def test_capture_writes_slo_artifact_and_heartbeat_block(tmp_path):
    d = str(tmp_path / "cap")
    obs.start_capture(d, heartbeat_interval_s=0.05, stall_timeout_s=None,
                      slo="lat=compute:p99_ms<=0.001@99%")
    with obs.span(names.SPAN_COMPUTE):
        time.sleep(0.01)  # guaranteed bad vs the 1 us threshold
    time.sleep(0.3)
    hb = json.loads(
        open(os.path.join(d, "progress.json")).read()
    )
    obs.finish_capture()
    assert hb["schema"] >= 4
    assert "lat" in hb["slo"]["objectives"]
    assert hb["slo"]["breached"] == ["lat"]
    doc = json.loads(open(os.path.join(d, "slo.json")).read())
    assert doc["objectives"]["lat"]["breach"] is True
    # the report renders the section and the watch line flags it
    from pta_replicator_tpu.obs.report import (
        render_heartbeat,
        render_report,
    )

    text = render_report(d)
    assert "slo (error budgets" in text and "BREACH" in text
    assert "SLO BREACH lat" in render_heartbeat(hb)
    # and the schema checker accepts the whole fresh capture
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "check_telemetry_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    assert checker.main([d]) == 0


def test_readyz_503_on_fast_burn_breach(tmp_path):
    """The /readyz half of the readiness ladder: a live heartbeat with
    a breaching slo.json is 503 slo-breach on /readyz while /healthz
    stays 200 (liveness must not restart a burning-but-alive server)."""
    import urllib.error
    import urllib.request

    from pta_replicator_tpu.obs.serve import serve_directory, serve_url

    d = str(tmp_path / "cap")
    os.makedirs(d)
    with open(os.path.join(d, "progress.json"), "w") as fh:
        json.dump({"schema": 4}, fh)
    with open(os.path.join(d, "slo.json"), "w") as fh:
        json.dump({"objectives": {"serve": {"breach": True}},
                   "breached": ["serve"]}, fh)
    srv = serve_directory(d, 0, background=True)
    try:
        with urllib.request.urlopen(
            serve_url(srv, "/healthz"), timeout=5.0
        ) as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(serve_url(srv, "/readyz"),
                                   timeout=5.0)
        assert exc.value.code == 503
        doc = json.loads(exc.value.read())
        assert doc["state"] == "slo-breach"
        assert doc["breached"] == ["serve"]
        # /slo serves the artifact itself
        with urllib.request.urlopen(
            serve_url(srv, "/slo"), timeout=5.0
        ) as r:
            assert json.loads(r.read())["breached"] == ["serve"]
        # recovery: no breach -> readyz back to 200
        with open(os.path.join(d, "slo.json"), "w") as fh:
            json.dump({"objectives": {"serve": {"breach": False}},
                       "breached": []}, fh)
        with urllib.request.urlopen(
            serve_url(srv, "/readyz"), timeout=5.0
        ) as r:
            assert r.status == 200
    finally:
        srv.shutdown()
        srv.server_close()


# ----------------------------------------------------- timeline flows

def test_timeline_renders_request_trace_flows(tmp_path, setup):
    from pta_replicator_tpu.obs.timeline import build_timeline

    d = str(tmp_path / "cap")
    obs.start_capture(d, flight_recorder=False)
    batch, recipe, bank = setup
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank), batch, recipe,
        axes=("rn_log10_amplitude",), max_batch=4, max_delay_s=0.002,
    )
    with server:
        futs = [server.submit(rn_log10_amplitude=-13.5 - 0.01 * i)
                for i in range(4)]
        for f in futs:
            f.result(timeout=60)
    obs.finish_capture()
    doc = build_timeline(d)
    assert doc["otherData"]["trace_flow_events"] > 0
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "trace"]
    by_id = {}
    for f in flows:
        by_id.setdefault(f["id"], []).append(f)
    # every chain is a well-formed s..t..f arrow
    for chain in by_id.values():
        phs = [f["ph"] for f in sorted(chain, key=lambda f: f["ts"])]
        assert phs[0] == "s" and phs[-1] == "f" and len(phs) >= 2
    # each request's chain carries its trace id in args
    chain_tids = {f["args"]["trace_id"] for f in flows}
    assert {f.trace_id for f in futs} <= chain_tids


# ------------------------------------------------- bench-diff contract

def test_trace_bench_diff_directions():
    from pta_replicator_tpu.obs.regress import bench_diff, metric_direction

    assert metric_direction("serving.stitched_fraction") is True
    assert metric_direction(
        "slo.error_budget_remaining{objective=serve}"
    ) is True
    assert metric_direction("admit.burn_rate_fast") is False
    assert metric_direction("admit.burn_rate_slow") is False
    assert metric_direction("serving.slo_breach_events") is False
    assert metric_direction("overhead.overhead_fraction") is False
    path = os.path.join(os.path.dirname(__file__), "..",
                        "TRACE_r14_cpu.json")
    assert os.path.exists(path), (
        "TRACE_r14_cpu.json must be committed with the request-trace "
        "bench evidence"
    )
    doc = json.loads(open(path).read())
    assert doc["ok"] and not doc["failures"]
    assert doc["serving"]["stitched_fraction"] == 1.0
    assert doc["overhead"]["overhead_fraction"] < 0.01
    _table, summary, rc = bench_diff([path, path])
    assert rc == 0 and summary["regressed"] == 0
