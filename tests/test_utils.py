"""Checkpoint round-trips, profiling hooks, native IO parity."""
import numpy as np
import pytest

from pta_replicator_tpu import add_red_noise, load_pulsar, make_ideal
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.io.tim import read_tim
from pta_replicator_tpu.utils.checkpoint import (
    load_batch,
    load_pulsar_checkpoint,
    save_batch,
    save_pulsar,
)
from pta_replicator_tpu.utils.profiling import reset, stage, timings

PAR = "/root/reference/test_partim_small/par/JPSR00.par"
TIM = "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim"


def test_pulsar_checkpoint_preserves_ledger(tmp_path):
    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)
    add_red_noise(psr, -14.0, 4.33, seed=5)
    p = tmp_path / "psr.npz"
    save_pulsar(psr, str(p))
    back = load_pulsar_checkpoint(str(p))
    assert back.name == psr.name
    # epochs survive at sub-ns; the ledger (lost by par/tim round-trips)
    # survives exactly
    assert float(np.abs((back.toas.mjd - psr.toas.mjd).astype(float)).max()) * 86400 < 1e-9
    key = f"{psr.name}_red_noise"
    np.testing.assert_array_equal(back.added_signals_time[key],
                                  psr.added_signals_time[key])
    assert back.added_signals[key]["spectral_index"] == 4.33
    np.testing.assert_allclose(back.residuals.resids_value,
                               psr.residuals.resids_value, atol=1e-9)


def test_batch_checkpoint_roundtrip(tmp_path):
    b = synthetic_batch(npsr=3, ntoa=40, seed=2)
    p = tmp_path / "batch.npz"
    save_batch(b, str(p))
    back = load_batch(str(p))
    assert back.names == b.names
    assert back.tref_mjd == b.tref_mjd
    np.testing.assert_array_equal(np.asarray(back.toas_s), np.asarray(b.toas_s))
    np.testing.assert_array_equal(np.asarray(back.epoch_index), np.asarray(b.epoch_index))


def test_profiling_stage_registry():
    reset()
    with stage("demo"):
        pass
    with stage("demo"):
        pass
    t = timings()
    assert t["demo"]["calls"] == 2
    assert t["demo"]["total_s"] >= 0


def test_native_tim_parser_matches_python():
    from pta_replicator_tpu.io.native import load_library

    if load_library() is None:
        pytest.skip("native toolchain unavailable")
    a = read_tim(TIM, use_native=True)
    b = read_tim(TIM, use_native=False)
    assert a.ntoas == b.ntoas
    assert float(np.abs((a.mjd - b.mjd).astype(float)).max()) == 0.0
    assert np.array_equal(a.errors_s, b.errors_s)
    assert a.flags == b.flags and a.observatories == b.observatories


def test_sweep_resume_bit_identical(tmp_path):
    """A sweep interrupted mid-way resumes from its checkpoint and yields
    results bit-identical to an uninterrupted run; finished sweeps return
    from disk; mismatched arguments are rejected."""
    import jax
    import jax.numpy as jnp
    from pta_replicator_tpu.models.batched import Recipe
    from pta_replicator_tpu.utils.sweep import sweep

    b = synthetic_batch(npsr=3, ntoa=64, seed=2)
    recipe = Recipe(efac=jnp.ones(3), rn_log10_amplitude=jnp.full(3, -14.0),
                    rn_gamma=jnp.full(3, 4.0))
    key = jax.random.PRNGKey(5)
    ck1 = str(tmp_path / "a.npz")
    full = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck1)
    assert full.shape == (16, 3)

    # interrupt after 2 of 4 chunks via the progress callback
    ck2 = str(tmp_path / "b.npz")

    class Stop(Exception):
        pass

    def bomb(done, total):
        if done == 2:
            raise Stop

    with pytest.raises(Stop):
        sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck2,
              progress=bomb)
    calls = []
    resumed = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck2,
                    progress=lambda d, t: calls.append(d))
    assert calls == [3, 4]  # only the remaining chunks ran
    np.testing.assert_array_equal(resumed, full)

    # finished sweep: zero chunks run, same result
    calls.clear()
    again = sweep(key, b, recipe, nreal=16, chunk=4, checkpoint_path=ck2,
                  progress=lambda d, t: calls.append(d))
    assert calls == []
    np.testing.assert_array_equal(again, full)

    with pytest.raises(ValueError, match="different sweep"):
        sweep(key, b, recipe, nreal=32, chunk=4, checkpoint_path=ck2)
    # different physics (recipe contents) must be rejected too
    import dataclasses

    other = dataclasses.replace(recipe, rn_gamma=jnp.full(3, 2.0))
    with pytest.raises(ValueError, match="different sweep"):
        sweep(key, b, other, nreal=16, chunk=4, checkpoint_path=ck2)
    # chunk files are consolidated away after completion
    import glob

    assert glob.glob(ck2 + ".chunk*") == []


def test_materialize_realizations_roundtrip(tmp_path, psrs_small):
    """Device realizations materialize as loadable par/tim datasets whose
    TOA shifts equal the injected delays, and the template pulsars are
    restored bitwise afterwards."""
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu import load_pulsar
    from pta_replicator_tpu.batch import freeze
    from pta_replicator_tpu.models.batched import (
        Recipe,
        deterministic_delays,
        realization_delays,
    )
    from pta_replicator_tpu.utils import materialize_realizations

    psrs = psrs_small
    batch = freeze(psrs)
    npsr = batch.npsr
    recipe = Recipe(
        efac=jnp.ones((npsr,), batch.toas_s.dtype),
        rn_log10_amplitude=jnp.full(npsr, -13.5, batch.toas_s.dtype),
        rn_gamma=jnp.full(npsr, 3.0, batch.toas_s.dtype),
    )
    mjd_before = [p.toas.mjd.copy() for p in psrs]
    ledgers_before = [dict(p.added_signals) for p in psrs]

    key = jax.random.PRNGKey(11)
    nreal = 2
    outdir = tmp_path / "datasets"
    dirs = materialize_realizations(
        psrs, batch, recipe, key, nreal=nreal, outdir=str(outdir), chunk=2
    )
    assert len(dirs) == nreal

    # template pulsars restored bitwise
    for p, m0, l0 in zip(psrs, mjd_before, ledgers_before):
        assert np.array_equal(np.asarray(p.toas.mjd), np.asarray(m0))
        assert dict(p.added_signals) == l0

    # written dataset r carries exactly realization r's pre-fit delays
    keys = jax.random.split(key, nreal)
    static = deterministic_delays(batch, recipe)
    for r, rdir in enumerate(dirs):
        want = np.asarray(realization_delays(keys[r], batch, recipe) + static)
        for i, p in enumerate(psrs):
            re = load_pulsar(
                str(tmp_path / "datasets" / f"real{r:05d}" / f"{p.name}.par"),
                str(tmp_path / "datasets" / f"real{r:05d}" / f"{p.name}.tim"),
            )
            # subtract in longdouble BEFORE casting: a float64 MJD cast
            # quantizes at ~0.6 us, swamping the ~ns tim serialization
            shift_s = np.asarray(
                (re.toas.mjd - p.toas.mjd) * np.longdouble(86400.0),
                np.float64,
            )
            n = p.toas.ntoas
            # tim files serialize ~sub-ns MJD precision; delays are ~1e-6 s
            np.testing.assert_allclose(
                shift_s, want[i, :n], atol=2e-9, rtol=0
            )


def test_batch_checkpoint_pre_frequency_format(tmp_path):
    """Batch checkpoints written before PulsarBatch carried observing
    frequencies load with freqs_mhz=None (and the chromatic op then
    raises its actionable error) instead of crashing on the missing key."""
    import jax

    from pta_replicator_tpu.models import batched as B

    b = synthetic_batch(npsr=2, ntoa=32, nbackend=2, seed=0)
    p = tmp_path / "b.npz"
    save_batch(b, str(p))
    # rewrite the npz without the freqs_mhz array = the old format
    data = dict(np.load(str(p), allow_pickle=False))
    data.pop("freqs_mhz")
    np.savez(str(p), **data)

    back = load_batch(str(p))
    assert back.freqs_mhz is None
    np.testing.assert_array_equal(np.asarray(back.toas_s), np.asarray(b.toas_s))
    with pytest.raises(ValueError, match="freqs_mhz"):
        B.chromatic_noise_delays(jax.random.PRNGKey(0), back, -13.5, 3.0)
