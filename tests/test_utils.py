"""Checkpoint round-trips, profiling hooks, native IO parity."""
import numpy as np
import pytest

from pta_replicator_tpu import add_red_noise, load_pulsar, make_ideal
from pta_replicator_tpu.batch import synthetic_batch
from pta_replicator_tpu.io.tim import read_tim
from pta_replicator_tpu.utils.checkpoint import (
    load_batch,
    load_pulsar_checkpoint,
    save_batch,
    save_pulsar,
)
from pta_replicator_tpu.utils.profiling import reset, stage, timings

PAR = "/root/reference/test_partim_small/par/JPSR00.par"
TIM = "/root/reference/test_partim_small/tim/fake_JPSR00_noiseonly.tim"


def test_pulsar_checkpoint_preserves_ledger(tmp_path):
    psr = load_pulsar(PAR, TIM)
    make_ideal(psr)
    add_red_noise(psr, -14.0, 4.33, seed=5)
    p = tmp_path / "psr.npz"
    save_pulsar(psr, str(p))
    back = load_pulsar_checkpoint(str(p))
    assert back.name == psr.name
    # epochs survive at sub-ns; the ledger (lost by par/tim round-trips)
    # survives exactly
    assert float(np.abs((back.toas.mjd - psr.toas.mjd).astype(float)).max()) * 86400 < 1e-9
    key = f"{psr.name}_red_noise"
    np.testing.assert_array_equal(back.added_signals_time[key],
                                  psr.added_signals_time[key])
    assert back.added_signals[key]["spectral_index"] == 4.33
    np.testing.assert_allclose(back.residuals.resids_value,
                               psr.residuals.resids_value, atol=1e-9)


def test_batch_checkpoint_roundtrip(tmp_path):
    b = synthetic_batch(npsr=3, ntoa=40, seed=2)
    p = tmp_path / "batch.npz"
    save_batch(b, str(p))
    back = load_batch(str(p))
    assert back.names == b.names
    assert back.tref_mjd == b.tref_mjd
    np.testing.assert_array_equal(np.asarray(back.toas_s), np.asarray(b.toas_s))
    np.testing.assert_array_equal(np.asarray(back.epoch_index), np.asarray(b.epoch_index))


def test_profiling_stage_registry():
    reset()
    with stage("demo"):
        pass
    with stage("demo"):
        pass
    t = timings()
    assert t["demo"]["calls"] == 2
    assert t["demo"]["total_s"] >= 0


def test_native_tim_parser_matches_python():
    from pta_replicator_tpu.io.native import load_library

    if load_library() is None:
        pytest.skip("native toolchain unavailable")
    a = read_tim(TIM, use_native=True)
    b = read_tim(TIM, use_native=False)
    assert a.ntoas == b.ntoas
    assert float(np.abs((a.mjd - b.mjd).astype(float)).max()) == 0.0
    assert np.array_equal(a.errors_s, b.errors_s)
    assert a.flags == b.flags and a.observatories == b.observatories
